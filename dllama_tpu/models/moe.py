"""Mixture-of-experts FFN (Grok-1 / Mixtral).

Reference semantics (`/root/reference/src/grok1-tasks.cpp:56-243`):
router logits -> softmax over ALL experts -> top-k (k = n_active_experts,
the reference hard-codes 2) -> selected probs renormalized to sum 1 ->
per selected expert: ``down_e( up_e(x) * act(gate_e(x)) )`` weighted-summed.

TP mapping: every shard holds a 1/tp slice of EVERY expert (the reference
slices within experts, not across them — `/root/reference/src/transformer.cpp:479-487`),
so the expert einsums below shard exactly like w1/w2/w3 and no expert-routing
communication is needed. An optional ``ep`` mesh axis can additionally shard
the leading expert dim of the stacked tensors (expert parallelism — beyond
the reference's capabilities). Under *quantized* TP (shard_map,
parallel.quant_tp) the expert planes carry output-axis shards and ``tp_axis``
drives explicit per-expert hidden gathers, mirroring the dense FFN's
gather-before-w2 (`models.llama._dense_ffn`); the gathers live
in `parallel.collectives`.

Compute paths:

* Dense stacks / no layer index: evaluate all E experts, combine with a
  [.., E] weight matrix that is zero off the top-k — dense and MXU-friendly,
  exact same math. For small E (8) that trades <=E/k extra FLOPs for zero
  gather/scatter.
* Quantized stacks under the scalar-prefetch layer scan (``layer`` given):
  the expert planes stay layer-stacked ([L, E, ...] folded to [L*E, ...], a
  free bitcast) and a traced ``layer * E + e`` steers each fused kernel's
  DMA. For small T (decode T==1, speculative verify T==k_spec+1) only the
  UNION of the rows' top-k selected experts is computed — at most
  min(E, T*k) expert plane reads instead of E — the bandwidth win that
  makes Q40 Grok-1-class models decode at quantized speed, the analog of
  the reference running only active experts
  (`/root/reference/src/grok1-tasks.cpp:128-143`). For batched prefill every
  expert runs once (different rows pick different experts) with the same
  zero-copy indexing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dllama_tpu.models.config import ModelConfig
from dllama_tpu.ops.activations import ACTIVATIONS
from dllama_tpu.ops.qmatmul import QuantTensor, matmul_any, slice_to_in_features
from dllama_tpu.parallel.collectives import gather_columns as _gather


def route_topk(cfg: ModelConfig, router_kernel: jnp.ndarray,
               xb: jnp.ndarray) -> tuple:
    """Top-k routing -> (indices [..., k], renormalized weights [..., k]).

    Router math runs in f32 like the reference (router matmul outputs F32,
    `/root/reference/src/grok1-tasks.cpp:56-60`); selected probabilities are
    renormalized to sum 1 (`:99-114`). Single source of truth for BOTH the
    dense-combine path and the T==1 selected-experts decode path — they must
    agree exactly or decode would diverge from prefill on the same weights.
    """
    logits = xb.astype(jnp.float32) @ router_kernel.astype(jnp.float32)  # [..., E]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, cfg.n_active_experts)
    weights = topv / topv.sum(axis=-1, keepdims=True)  # renormalize over selected
    return topi, weights


def route(cfg: ModelConfig, router_kernel: jnp.ndarray, xb: jnp.ndarray) -> jnp.ndarray:
    """Top-k routing -> dense combine weights [..., E] (zeros off the top-k)."""
    topi, weights = route_topk(cfg, router_kernel, xb)
    one_hot = jax.nn.one_hot(topi, cfg.n_experts, dtype=jnp.float32)  # [..., k, E]
    return jnp.einsum("...ke,...k->...e", one_hot, weights.astype(jnp.float32))


def _flat_experts(qt: QuantTensor) -> QuantTensor:
    """Fold a layer-stacked expert stack [L, E, ...] (or a per-layer stack
    [E, ...]) to a flat [n, ...] stack for index-steered kernels. Leading-axis
    reshapes are bitcasts — no copy, the planes stay in place in HBM."""
    return QuantTensor(
        w=qt.w.reshape(-1, *qt.w.shape[-2:]),
        s=qt.s.reshape(-1, *qt.s.shape[-2:]),
        s2=(qt.s2.reshape(-1, *qt.s2.shape[-2:]) if qt.kind == "q40"
            else qt.s2.reshape(-1)),
        kind=qt.kind, k_logical=qt.k_logical,
    )


def _expert_up(xb: jnp.ndarray, w, base=None) -> jnp.ndarray:
    """``xb [..., D] x w [E, D, H] -> [..., E, H]``; ``w`` is a dense stack or
    an expert-stacked QuantTensor. Quantized experts run one fused
    dequant-matmul per expert; with ``base`` (= layer * E, the scalar-prefetch
    path) the planes are layer-stacked and indexed in the kernel, otherwise
    the scan slices the per-layer stack."""
    if not isinstance(w, QuantTensor):
        return jnp.einsum("...d,edh->...eh", xb, w)
    lead = xb.shape[:-1]
    x2 = xb.reshape(-1, xb.shape[-1])  # [N, D]

    if base is not None:
        flat = _flat_experts(w)
        n_e = w.w.shape[1]

        def step(_, e):
            return None, matmul_any(x2, flat, base + e)

        _, outs = jax.lax.scan(step, None, jnp.arange(n_e, dtype=jnp.int32))
    else:
        def step(_, qt_e):
            return None, matmul_any(x2, qt_e)

        _, outs = jax.lax.scan(step, None, w)  # [E, N, H]
    return jnp.moveaxis(outs, 0, 1).reshape(*lead, outs.shape[0], outs.shape[-1])


def _expert_down(h: jnp.ndarray, w, base=None) -> jnp.ndarray:
    """``h [..., E, H] x w [E, H, D] -> [..., E, D]`` (dense or QuantTensor)."""
    if not isinstance(w, QuantTensor):
        return jnp.einsum("...eh,ehd->...ed", h, w)
    lead = h.shape[:-2]
    E, H = h.shape[-2], h.shape[-1]
    hm = jnp.moveaxis(h.reshape(-1, E, H), 1, 0)  # [E, N, H]

    if base is not None:
        flat = _flat_experts(w)

        def step(_, eh):
            e, h_e = eh
            return None, matmul_any(h_e, flat, base + e)

        _, outs = jax.lax.scan(
            step, None, (jnp.arange(E, dtype=jnp.int32), hm))
    else:
        def step(_, eh):
            h_e, qt_e = eh
            return None, matmul_any(h_e, qt_e)

        _, outs = jax.lax.scan(step, None, (hm, w))  # [E, N, D]
    return jnp.moveaxis(outs, 0, 1).reshape(*lead, E, outs.shape[-1])


def _moe_decode_selected(cfg: ModelConfig, lp: dict, xb: jnp.ndarray, layer,
                         tp_axis=None, tp_compress: bool = False) -> jnp.ndarray:
    """Small-T decode/verify with layer-stacked quantized experts: run ONLY
    the union of the rows' top-k selected experts, each kernel DMA-ing just
    that expert's planes. T==1 is plain decode (the union is exactly the
    top-k); T==k_spec+1 is a speculative verify step, which still reads at
    most min(E, T*k) expert plane sets instead of all E. Exact same math as
    the dense combine: every expert outside the union has zero combine
    weight for every row, and union slots beyond the actually-selected set
    (ties in the top-cap selection) multiply a zero weight.

    Under quantized TP (``tp_axis``): the expert planes are output shards;
    all selected experts' hidden activations are gathered in ONE collective
    (decode payloads are latency-bound — collective count matters more than
    bytes, see ``parallel.collectives``), then each feeds its down matmul and the
    combined output — accumulated in output shards — is gathered at the end:
    2 collectives per MoE FFN, like the dense FFN's pair.
    """
    act = ACTIVATIONS[cfg.hidden_act]
    E, k = cfg.n_experts, cfg.n_active_experts
    T = xb.shape[0]
    cap = min(E, T * k)
    combine = route(cfg, lp["moe_router"], xb)  # [T, E] f32, zero off top-k
    # every expert any row selected has a positive combine weight somewhere,
    # and there are at most T*k of them — the top `cap` column-maxima cover
    # the whole union (extra slots carry zero weight and contribute nothing)
    _, expert_ids = jax.lax.top_k(combine.max(axis=0), cap)  # [cap]
    base = layer * E

    fused = "moe_upgate" in lp
    up_flat = _flat_experts(lp["moe_upgate" if fused else "moe_up"])
    gate_flat = None if fused else _flat_experts(lp["moe_gate"])
    down_flat = _flat_experts(lp["moe_down"])
    out_dim = down_flat.out_features  # local under tp, full otherwise

    def up_step(_, j):
        idx = base + expert_ids[j]
        if fused:
            ug = matmul_any(xb, up_flat, idx)
            half = ug.shape[-1] // 2
            h = ug[..., :half] * act(ug[..., half:])
        else:
            h = matmul_any(xb, up_flat, idx) * act(matmul_any(xb, gate_flat, idx))
        return None, h

    _, hs = jax.lax.scan(up_step, None, jnp.arange(cap, dtype=jnp.int32))
    hs = _gather(hs, tp_axis, tp_compress)  # [cap, T, full hidden] in one hop

    def down_step(acc, jh):
        j, h = jh
        e = expert_ids[j]
        d = matmul_any(h, down_flat, base + e)  # [T, out_dim]
        w_e = jax.lax.dynamic_index_in_dim(combine, e, axis=1)  # [T, 1]
        return acc + d * w_e.astype(d.dtype), None

    acc, _ = jax.lax.scan(
        down_step, jnp.zeros((T, out_dim), xb.dtype),
        (jnp.arange(cap, dtype=jnp.int32), hs))
    return _gather(acc, tp_axis, tp_compress)


def moe_ffn(cfg: ModelConfig, lp: dict, xb: jnp.ndarray, layer=None,
            tp_axis=None, tp_compress: bool = False) -> jnp.ndarray:
    """MoE FFN over xb [..., dim] -> [..., dim].

    lp holds: moe_router [dim, E], moe_up/moe_gate [E, dim, hidden],
    moe_down [E, hidden, dim] — each expert stack a dense array or a
    quantized (QuantTensor) stack. With ``layer`` (the scalar-prefetch scan),
    quantized stacks carry a leading layer axis and dense leaves arrive
    already layer-indexed. ``tp_axis`` (inside shard_map, quantized TP):
    expert stacks are output shards; the hidden activation is gathered
    before the down matmuls and the output once after the combine.
    """
    act = ACTIVATIONS[cfg.hidden_act]
    up_names = ("moe_upgate",) if "moe_upgate" in lp else ("moe_up", "moe_gate")
    quant_experts = all(
        isinstance(lp.get(n), QuantTensor) for n in up_names + ("moe_down",)
    )
    if (layer is not None and quant_experts and xb.ndim == 2
            and xb.shape[0] * cfg.n_active_experts < cfg.n_experts):
        return _moe_decode_selected(cfg, lp, xb, layer, tp_axis, tp_compress)

    # Under the layer scan, EVERY QuantTensor stack is layer-stacked and needs
    # index-steered kernels — even if a sibling stack fell back to dense (the
    # hidden_dim % 64 != 0 load fallback), which arrives already layer-indexed
    # and ignores base. A global quant_experts gate here would feed a 4D
    # [L, E, ...] stack into the per-expert slicing scan below.
    base = layer * cfg.n_experts if layer is not None else None
    combine = route(cfg, lp["moe_router"], xb).astype(xb.dtype)  # [..., E]

    if "moe_upgate" in lp:  # fused up|gate expert stacks (llama.fuse_qkv_ffn)
        ug = _expert_up(xb, lp["moe_upgate"], base)
        half = ug.shape[-1] // 2
        h = ug[..., :half] * act(ug[..., half:])
    else:
        up = _expert_up(xb, lp["moe_up"], base)
        gate = _expert_up(xb, lp["moe_gate"], base)
        h = up * act(gate)
    h = _gather(h, tp_axis, tp_compress)  # [..., E, full hidden] under tp
    h = slice_to_in_features(h, lp["moe_down"])
    down = _expert_down(h, lp["moe_down"], base)
    out = jnp.einsum("...ed,...e->...d", down, combine)
    return _gather(out, tp_axis, tp_compress)
