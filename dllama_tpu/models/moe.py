"""Mixture-of-experts FFN (Grok-1 / Mixtral).

Reference semantics (`/root/reference/src/grok1-tasks.cpp:56-243`):
router logits -> softmax over ALL experts -> top-k (k = n_active_experts,
the reference hard-codes 2) -> selected probs renormalized to sum 1 ->
per selected expert: ``down_e( up_e(x) * act(gate_e(x)) )`` weighted-summed.

TP mapping: every shard holds a 1/tp slice of EVERY expert (the reference
slices within experts, not across them — `/root/reference/src/transformer.cpp:479-487`),
so the expert einsums below shard exactly like w1/w2/w3 and no expert-routing
communication is needed. An optional ``ep`` mesh axis can additionally shard
the leading expert dim of the stacked tensors (expert parallelism — beyond
the reference's capabilities).

Compute note: this evaluates all E experts and combines with a [.., E] weight
matrix that is zero off the top-k — dense and MXU-friendly, exact same math.
For small E (8) that trades <=E/k extra FLOPs for zero gather/scatter; a
megablocks-style grouped kernel is the later optimization for big-E models.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dllama_tpu.models.config import ModelConfig
from dllama_tpu.ops.activations import ACTIVATIONS
from dllama_tpu.ops.qmatmul import QuantTensor, matmul_any


def route(cfg: ModelConfig, router_kernel: jnp.ndarray, xb: jnp.ndarray) -> jnp.ndarray:
    """Top-k routing -> dense combine weights [..., E] (zeros off the top-k).

    Router math runs in f32 like the reference (router matmul outputs F32,
    `/root/reference/src/grok1-tasks.cpp:56-60`).
    """
    logits = xb.astype(jnp.float32) @ router_kernel.astype(jnp.float32)  # [..., E]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, cfg.n_active_experts)
    weights = topv / topv.sum(axis=-1, keepdims=True)  # renormalize over selected
    one_hot = jax.nn.one_hot(topi, cfg.n_experts, dtype=jnp.float32)  # [..., k, E]
    return jnp.einsum("...ke,...k->...e", one_hot, weights)


def _expert_up(xb: jnp.ndarray, w) -> jnp.ndarray:
    """``xb [..., D] x w [E, D, H] -> [..., E, H]``; ``w`` is a dense stack or
    an expert-stacked QuantTensor (leading E axis on every plane). Quantized
    experts run one fused dequant-matmul per expert via lax.scan over the
    stack — the per-expert twin of the reference's sliced expert matmuls
    (`/root/reference/src/grok1-tasks.cpp:128-143`, Q40 weights per
    `/root/reference/src/transformer.cpp:479-487`)."""
    if not isinstance(w, QuantTensor):
        return jnp.einsum("...d,edh->...eh", xb, w)
    lead = xb.shape[:-1]
    x2 = xb.reshape(-1, xb.shape[-1])  # [N, D]

    def step(_, qt_e):
        return None, matmul_any(x2, qt_e)

    _, outs = jax.lax.scan(step, None, w)  # [E, N, H]
    return jnp.moveaxis(outs, 0, 1).reshape(*lead, outs.shape[0], outs.shape[-1])


def _expert_down(h: jnp.ndarray, w) -> jnp.ndarray:
    """``h [..., E, H] x w [E, H, D] -> [..., E, D]`` (dense or QuantTensor)."""
    if not isinstance(w, QuantTensor):
        return jnp.einsum("...eh,ehd->...ed", h, w)
    lead = h.shape[:-2]
    E, H = h.shape[-2], h.shape[-1]
    hm = jnp.moveaxis(h.reshape(-1, E, H), 1, 0)  # [E, N, H]

    def step(_, eh):
        h_e, qt_e = eh
        return None, matmul_any(h_e, qt_e)

    _, outs = jax.lax.scan(step, None, (hm, w))  # [E, N, D]
    return jnp.moveaxis(outs, 0, 1).reshape(*lead, E, outs.shape[-1])


def moe_ffn(cfg: ModelConfig, lp: dict, xb: jnp.ndarray) -> jnp.ndarray:
    """MoE FFN over xb [..., dim] -> [..., dim].

    lp holds: moe_router [dim, E], moe_up/moe_gate [E, dim, hidden],
    moe_down [E, hidden, dim] — each expert stack a dense array or a
    quantized (QuantTensor) stack.
    """
    act = ACTIVATIONS[cfg.hidden_act]
    combine = route(cfg, lp["moe_router"], xb).astype(xb.dtype)  # [..., E]

    if "moe_upgate" in lp:  # fused up|gate expert stacks (llama.fuse_qkv_ffn)
        ug = _expert_up(xb, lp["moe_upgate"])
        half = ug.shape[-1] // 2
        h = ug[..., :half] * act(ug[..., half:])
    else:
        up = _expert_up(xb, lp["moe_up"])
        gate = _expert_up(xb, lp["moe_gate"])
        h = up * act(gate)
    down = _expert_down(h, lp["moe_down"])
    return jnp.einsum("...ed,...e->...d", down, combine)
