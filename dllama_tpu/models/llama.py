"""Dense Llama-family transformer — the single-program SPMD forward pass.

Where the reference unrolls 25 root + 15 worker task functions per layer with
explicit broadcast/gather between them (`/root/reference/src/llama2-tasks.cpp:243-300`),
here the whole forward pass is one jitted function: a ``lax.scan`` over stacked
layer parameters, with tensor-parallel sharding expressed as PartitionSpecs
(see ``dllama_tpu.parallel``) so XLA emits the collectives the reference
hand-rolls over TCP.

Math parity notes:
* rmsnorm eps semantics: `/root/reference/src/funcs.cpp:94-123`.
* attention: `/root/reference/src/llama2-tasks.cpp:54-94` (see ops.attention).
* SwiGLU: ``w2( act(w1 x) * (w3 x) )`` — `/root/reference/src/llama2-tasks.cpp:158-189`.
* logits: final rmsnorm then ``wcls`` matmul — `/root/reference/src/llama2-tasks.cpp:222-241`.

Weights use kernel layout ``[in_features, out_features]`` (transposed from the
file's ``[out, in]`` rows) so activations hit the MXU as plain ``x @ w``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.formats.weights import WeightFileReader
from dllama_tpu.models.config import ModelConfig
from dllama_tpu.ops import flash_decode, fused_rope_cache
from dllama_tpu.ops.activations import ACTIVATIONS
from dllama_tpu.ops.attention import gqa_attention
from dllama_tpu.ops.norms import rmsnorm
from dllama_tpu.ops.qmatmul import (
    QuantTensor, matmul_any, norm_fusion_engages, qmatmul_norm,
    quantize_tensor, slice_to_in_features,
)
from dllama_tpu.ops.rope import apply_rope, rope_table
from dllama_tpu.parallel.collectives import (
    gather_columns as _gather,
    reduce_scatter_columns as _reduce_scatter,
    rms_inv_scattered as _rms_inv,
    scatter_features as _scatter,
)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def iter_param_tensors(reader: WeightFileReader, cfg: ModelConfig, dtype=None):
    """Yield ``(path, array)`` pairs of the stacked-layer pytree, one tensor
    at a time — ``path`` is ``("embedding",)`` / ``("layers", "wq")`` / etc.

    The streaming unit is one *stacked* tensor (all layers of one matrix), so
    peak host memory is one [L, in, out] array rather than the whole model —
    the TPU analog of the reference's slice-streaming weight distribution
    where no worker ever holds more than its share
    (`/root/reference/src/transformer.cpp:569-598`). Exception: MoE expert
    stacks stream as one [L, E, in, out] tensor per up/gate/down — all
    experts of all layers at once (~1/3 of a Mixtral-class model on the
    host); per-layer expert streaming is future work."""
    dtype = dtype or cfg.jax_dtype
    yield ("embedding",), reader.read_tensor("token_embedding", np.float32)
    yield ("rms_final",), reader.read_tensor("rms_final", np.float32)
    yield ("wcls",), reader.read_tensor("wcls", dtype).T

    mat_names = ["wq", "wk", "wv", "wo"] + ([] if cfg.is_moe else ["w1", "w2", "w3"])
    vec_names = ["rms_att", "rms_ffn"] + (["rms_moe", "rms_ffn2"] if cfg.post_norms else [])
    for n in mat_names:
        yield ("layers", n), np.stack(
            [reader.read_tensor(f"layers.{i}.{n}", dtype).T for i in range(cfg.n_layers)]
        )  # [L, in, out]
    if cfg.is_moe:
        yield ("layers", "moe_router"), np.stack(
            [reader.read_tensor(f"layers.{i}.moe_router", dtype).T for i in range(cfg.n_layers)]
        )
        for kind in ("up", "gate", "down"):
            yield ("layers", f"moe_{kind}"), np.stack(
                [
                    np.stack(
                        [
                            reader.read_tensor(f"layers.{i}.experts.{e}.{kind}", dtype).T
                            for e in range(cfg.n_experts)
                        ]
                    )
                    for i in range(cfg.n_layers)
                ]
            )  # [L, E, in, out]
    for n in vec_names:
        yield ("layers", n), np.stack(
            [reader.read_tensor(f"layers.{i}.{n}", np.float32) for i in range(cfg.n_layers)]
        )


def assemble_params(pairs, transform=None) -> dict:
    """Build the param pytree from ``iter_param_tensors`` pairs, applying
    ``transform(path, arr)`` to each leaf (identity when None). The single
    place that knows the path -> pytree mapping, shared by the full and the
    streaming-sharded loaders."""
    p: dict = {"layers": {}}
    for path, arr in pairs:
        leaf = transform(path, arr) if transform is not None else arr
        if path[0] == "layers":
            p["layers"][path[1]] = leaf
        else:
            p[path[0]] = leaf
    return p


def params_from_reader(reader: WeightFileReader, cfg: ModelConfig, dtype=None) -> dict:
    """Load `.m` tensors into the stacked-layer pytree (dense and MoE archs)."""
    return assemble_params(iter_param_tensors(reader, cfg, dtype))


#: per-layer matrices eligible for fused-quantized storage
QUANTIZABLE = ("wq", "wk", "wv", "wo", "w1", "w2", "w3")
#: expert stacks [L, E, in, out] eligible for fused-quantized storage
MOE_QUANTIZABLE = ("moe_up", "moe_gate", "moe_down")


def quantize_params(params: dict, kind: str, quantize_wcls: bool = True) -> dict:
    """Convert dense layer matrices (and wcls) into stacked ``QuantTensor``s
    for the fused dequant-matmul kernels (ops.qmatmul). Embedding, norms and
    the MoE router stay dense f32 — same split as the reference, which keeps
    rms weights and the embedding table F32 whatever the weight type
    (`/root/reference/converter/convert-llama.py:78-84`; router logits are F32
    at `/root/reference/src/grok1-tasks.cpp:56-60`)."""
    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name in QUANTIZABLE:
        if name not in out["layers"]:
            continue
        stacked = np.asarray(
            jax.device_get(out["layers"][name]), np.float32
        )  # [L, in, out]
        qts = [quantize_tensor(stacked[i], kind) for i in range(stacked.shape[0])]
        out["layers"][name] = jax.tree.map(lambda *xs: jnp.stack(xs), *qts)
    for name in MOE_QUANTIZABLE:
        if name not in out["layers"]:
            continue
        stacked = np.asarray(
            jax.device_get(out["layers"][name]), np.float32
        )  # [L, E, in, out]
        per_layer = [
            jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[quantize_tensor(stacked[l, e], kind) for e in range(stacked.shape[1])],
            )
            for l in range(stacked.shape[0])
        ]
        out["layers"][name] = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
    if quantize_wcls:
        wcls = np.asarray(jax.device_get(params["wcls"]), np.float32)
        out["wcls"] = quantize_tensor(wcls, kind)
    return out


def quant_params_from_reader(reader: WeightFileReader, cfg: ModelConfig,
                             kind: str = "q40", mesh=None,
                             fuse: bool = True,
                             tp_reduce: bool = False) -> dict:
    """Load a `.m` file with the big matrices kept block-quantized for the
    fused kernels. When the file's own float type matches ``kind``, the file
    bits are repacked losslessly (no dequant->requant roundtrip), so decode
    uses the exact published Q40/Q80 checkpoint values — the TPU equivalent
    of the reference's ``matmulQ40vQ80`` production path
    (`/root/reference/src/funcs.cpp:267-385`). MoE archs load their expert
    stacks as per-expert QuantTensors (the reference runs Q40 Grok-1 314B —
    `/root/reference/src/transformer.cpp:479-487` — a model class that cannot
    exist unquantized).

    Streaming: without a mesh, planes stay host numpy until one whole
    stacked tensor is assembled, then that tensor is placed. With ``mesh``,
    the host never holds more than ONE LAYER of any stacked tensor: each
    [L, ...] stack is preallocated straight into its TP sharding
    (``parallel.quant_tp`` output-axis specs) and filled layer by layer with
    donated in-place ``dynamic_update_slice`` writes. Peak host RAM is
    model_bytes / n_layers — how a Grok-1-314B-class Q40 file loads through
    an ordinary host — and no single device ever holds the full model
    (matching the reference's never-materialize-everything slice streaming,
    `/root/reference/src/transformer.cpp:569-598`)."""
    from dllama_tpu.ops import qmatmul as qm
    from dllama_tpu.quants import blocks

    file_ft = reader.spec.weights_float_type
    lossless = (kind == "q40" and file_ft == blocks.Q40) or (
        kind == "q80" and file_ft == blocks.Q80
    )
    repack = qm.repack_q40 if kind == "q40" else qm.repack_q80

    # the fused kernels need in_features divisible by the packing unit
    # (64 for the q40 nibble pairs, 32 = one block for q80)
    kernel_multiple = 64 if kind == "q40" else 32

    if mesh is not None:
        from jax.sharding import NamedSharding

        from dllama_tpu.parallel import quant_tp
        from dllama_tpu.parallel.mesh import TP

        n_tp = mesh.shape[TP]
        quant_tp.validate_quant_tp(cfg, n_tp)

        def place(name: str, leaf, sharded: bool):
            leaf = quant_tp.prepare_quant_leaf(name, leaf, cfg, n_tp,
                                               tp_reduce=tp_reduce)
            row = (tp_reduce and name in quant_tp.ROW_SHARDED_MATRICES
                   and isinstance(leaf, QuantTensor))
            specs = quant_tp.leaf_specs(leaf, sharded, row=row)
            return jax.tree.map(
                lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), leaf, specs
            )

        shard_wcls = cfg.vocab_size % n_tp == 0
    else:
        def place(name: str, leaf, sharded: bool):
            return jax.tree.map(jnp.asarray, leaf)

        shard_wcls = False

    def load_matrix(name: str):
        """Host-side (numpy-plane) QuantTensor or dense array for one matrix."""
        e = reader.entry(name)
        if e.n % kernel_multiple != 0:
            # valid in the file format (blocks are 32-wide) but not packable
            # for the kernel: keep this matrix dense instead of crashing
            return reader.read_tensor(name, cfg.jax_dtype).T
        if lossless:
            return repack(reader.read_raw(name), e.d, e.n, to_device=False)
        return quantize_tensor(
            reader.read_tensor(name, np.float32).T, kind, to_device=False
        )

    def np_stack(items):
        return jax.tree.map(lambda *xs: np.stack(xs), *items)

    p = {
        "embedding": place("embedding", reader.read_tensor("token_embedding", np.float32), False),
        "rms_final": place("rms_final", reader.read_tensor("rms_final", np.float32), False),
        "wcls": place("wcls", load_matrix("wcls"), shard_wcls),
    }
    mat_names = ("wq", "wk", "wv", "wo") if cfg.is_moe else QUANTIZABLE
    vec_names = ["rms_att", "rms_ffn"] + (
        ["rms_moe", "rms_ffn2"] if cfg.post_norms else []
    )
    from dllama_tpu.parallel.quant_tp import SHARDED_MATRICES

    def load_layer_leaf(i: int, n: str):
        pre = f"layers.{i}."
        if n == "moe_router":
            return reader.read_tensor(pre + "moe_router", cfg.jax_dtype).T
        if n.startswith("moe_"):
            return np_stack([
                load_matrix(f"{pre}experts.{e}.{n[4:]}")
                for e in range(cfg.n_experts)
            ])
        return load_matrix(pre + n)

    moe_names = ["moe_router", "moe_up", "moe_gate", "moe_down"] if cfg.is_moe else []

    if mesh is not None:
        # Streamed stacked placement: read one layer of one matrix at a
        # time, lane-align it, and write it into the preallocated SHARDED
        # device stack in place (donated dynamic_update_slice). The host
        # peak is a single layer's planes — for an MoE stack that is
        # 1/n_layers of the expert bytes, not all of them.
        # (quant_tp / NamedSharding are bound above in this mesh branch.)
        from functools import partial

        from jax.sharding import PartitionSpec as P

        @partial(jax.jit, donate_argnums=0)
        def insert(stack, leaf, idx):
            return jax.tree.map(
                lambda s, x: jax.lax.dynamic_update_slice(
                    s, x[None], (idx,) + (0,) * x.ndim),
                stack, leaf,
            )

        def stream_stack(name: str):
            sharded = name in SHARDED_MATRICES
            stack = None
            per_specs = None
            for i in range(cfg.n_layers):
                leaf = quant_tp.prepare_quant_leaf(
                    name, load_layer_leaf(i, name), cfg, n_tp,
                    tp_reduce=tp_reduce)
                if stack is None:
                    row = (tp_reduce
                           and name in quant_tp.ROW_SHARDED_MATRICES
                           and isinstance(leaf, QuantTensor))
                    per_specs = quant_tp.leaf_specs(leaf, sharded, row=row)
                    out_sh = jax.tree.map(
                        lambda x, s: NamedSharding(mesh, P(None, *tuple(s))),
                        leaf, per_specs,
                    )
                    alloc = jax.jit(
                        lambda l=leaf: jax.tree.map(
                            lambda x: jnp.zeros((cfg.n_layers,) + x.shape, x.dtype), l
                        ),
                        out_shardings=out_sh,
                    )
                    stack = alloc()
                leaf = jax.tree.map(
                    lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
                    leaf, per_specs,
                )
                stack = insert(stack, leaf, jnp.int32(i))
            return stack

        p["layers"] = {n: stream_stack(n) for n in list(mat_names) + moe_names}
        for n in vec_names:
            vec = np.stack([
                reader.read_tensor(f"layers.{i}.{n}", np.float32)
                for i in range(cfg.n_layers)
            ])
            p["layers"][n] = place(n, vec, False)
        return p

    layers: dict = {}
    for i in range(cfg.n_layers):
        for n in list(mat_names) + moe_names:
            layers.setdefault(n, []).append(load_layer_leaf(i, n))
        for n in vec_names:
            layers.setdefault(n, []).append(
                reader.read_tensor(f"layers.{i}.{n}", np.float32))
    p["layers"] = {k: np_stack(v) for k, v in layers.items()}
    if fuse:
        # single-device: fuse shared-input projections ON HOST (numpy planes)
        # before placement, so the unfused originals never reach HBM —
        # fusing after device placement would double weight residency
        p = fuse_qkv_ffn(p)
    p["layers"] = {
        k: place(k, v, k in SHARDED_MATRICES) for k, v in p["layers"].items()
    }
    return p


def fuse_qkv_ffn(params: dict) -> dict:
    """Concatenate quantized projection matrices that share an input into one
    kernel call each: wq|wk|wv -> ``wqkv`` [D, D+2KV], w1|w3 -> ``w13``
    [D, 2H], moe_up|moe_gate -> ``moe_upgate`` [E, D, 2H].

    Single-device decode win: 7 fused dequant-matmul launches per layer drop
    to 4, each with a larger grid that amortizes pipeline warm-up — the same
    bytes move, in fewer better-overlapped kernels. The forward recognizes
    the fused names and slices the outputs (slices on [T, O] activations are
    free next to the matmul). Quant concat is exact: planes are concatenated
    along the output axis, per-column scales travel with their columns.

    Only for unsharded (mesh-less) params: under TP each part must shard on
    its own output axis, so fusion would put shard boundaries inside the
    wrong matrix. The TP engine keeps the unfused layout.
    """
    out = dict(params)
    out["layers"] = layers = dict(params["layers"])

    def cat(*qts):
        def concat(*xs):
            xp = np if all(isinstance(x, np.ndarray) for x in xs) else jnp
            return xp.concatenate(xs, axis=-1)

        return jax.tree.map(concat, *qts)

    if all(isinstance(layers.get(n), QuantTensor) for n in ("wq", "wk", "wv")):
        layers["wqkv"] = cat(layers.pop("wq"), layers.pop("wk"), layers.pop("wv"))
    if all(isinstance(layers.get(n), QuantTensor) for n in ("w1", "w3")):
        layers["w13"] = cat(layers.pop("w1"), layers.pop("w3"))
    if all(isinstance(layers.get(n), QuantTensor) for n in ("moe_up", "moe_gate")):
        layers["moe_upgate"] = cat(layers.pop("moe_up"), layers.pop("moe_gate"))
    return out


def device_random_quant_params(cfg: ModelConfig, kind: str = "q40", seed: int = 0) -> dict:
    """Random *quantized* params built directly on device — the benchmark's
    7B-shape model with Q40/Q80 HBM residency and no host-side 7B pytree.
    The packed bits are random (valid nibbles/int8) with small scales; the
    model is numerically plausible but meaningless, like device_random_params.
    MoE configs get [L, E, ...] expert plane stacks (the loader's layout:
    TP-within-expert, every chip a slice of every expert) with a dense f32
    router, so Q40 Grok-1/Mixtral-shape decode is benchable without a
    checkpoint.

    The whole build runs as ONE jitted program: on a tunneled TPU, ~25 eager
    randint/astype dispatches are ~25 separate remote compiles + round trips
    (any of which can wedge a flaky tunnel mid-build); one program is one
    compile and one execute."""
    return jax.jit(_quant_init, static_argnums=(1, 2))(
        jax.random.PRNGKey(seed), cfg, kind
    )


def _quant_init(key, cfg: ModelConfig, kind: str) -> dict:
    L, D, H, KV = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.kv_dim
    ks = iter(jax.random.split(key, 32))

    def qrand(K_, O_, prefix=(L,)):
        """Random QuantTensor, shape prefix () for unstacked (wcls). The
        packed K is padded like pack_q40/pack_q80 (random pad bits are fine:
        padded activation rows are zero, so the pad contributes nothing)."""
        from dllama_tpu.ops.qmatmul import K_MULTIPLE, _pad_up

        kp = _pad_up(K_, K_MULTIPLE[kind])
        if kind == "q40":
            w = jax.random.randint(
                next(ks), (*prefix, kp // 2, O_), 0, 256, jnp.int32
            ).astype(jnp.uint8)
            s = jax.random.uniform(next(ks), (*prefix, kp // 64, O_), jnp.float32) * 0.004
            s2 = jax.random.uniform(next(ks), (*prefix, kp // 64, O_), jnp.float32) * 0.004
            return QuantTensor(w=w, s=s, s2=s2, kind="q40", k_logical=K_)
        w = jax.random.randint(next(ks), (*prefix, kp, O_), -127, 128, jnp.int8)
        s = jax.random.uniform(next(ks), (*prefix, kp // 32, O_), jnp.float32) * 0.0003
        return QuantTensor(
            w=w, s=s, s2=jnp.zeros((*prefix, 0), jnp.float32), kind="q80", k_logical=K_
        )

    layers = {
        "wq": qrand(D, D),
        "wk": qrand(D, KV),
        "wv": qrand(D, KV),
        "wo": qrand(D, D),
        "rms_att": jnp.ones((L, D), jnp.float32),
        "rms_ffn": jnp.ones((L, D), jnp.float32),
    }
    if cfg.is_moe:
        E = cfg.n_experts
        layers.update(
            moe_router=jax.random.normal(next(ks), (L, D, E), jnp.float32) * 0.02,
            moe_up=qrand(D, H, prefix=(L, E)),
            moe_gate=qrand(D, H, prefix=(L, E)),
            moe_down=qrand(H, D, prefix=(L, E)),
        )
        if cfg.post_norms:
            layers["rms_moe"] = jnp.ones((L, D), jnp.float32)
            layers["rms_ffn2"] = jnp.ones((L, D), jnp.float32)
    else:
        layers.update(w1=qrand(D, H), w3=qrand(D, H), w2=qrand(H, D))
    return {
        "embedding": jax.random.normal(next(ks), (cfg.vocab_size, D), jnp.float32) * 0.02,
        "rms_final": jnp.ones(D, jnp.float32),
        "wcls": qrand(D, cfg.vocab_size, prefix=()),
        "layers": layers,
    }


def random_params(cfg: ModelConfig, seed: int = 0, scale: float = 0.02, dtype=None) -> dict:
    """Seeded synthetic weights (the llama2-tasks-test pattern, for tests/bench)."""
    dtype = dtype or cfg.jax_dtype
    rng = np.random.default_rng(seed)

    def w(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32).astype(dtype)

    L, D, H, KV = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.kv_dim
    layers = {
        "wq": w(L, D, D),
        "wk": w(L, D, KV),
        "wv": w(L, D, KV),
        "wo": w(L, D, D),
        "rms_att": np.ones((L, D), np.float32),
        "rms_ffn": np.ones((L, D), np.float32),
    }
    if cfg.is_moe:
        E = cfg.n_experts
        layers.update(
            {
                "moe_router": w(L, D, E),
                "moe_up": w(L, E, D, H),
                "moe_gate": w(L, E, D, H),
                "moe_down": w(L, E, H, D),
            }
        )
        if cfg.post_norms:
            layers["rms_moe"] = np.ones((L, D), np.float32)
            layers["rms_ffn2"] = np.ones((L, D), np.float32)
    else:
        layers.update({"w1": w(L, D, H), "w2": w(L, H, D), "w3": w(L, D, H)})
    return {
        "embedding": w(cfg.vocab_size, D).astype(np.float32),
        "rms_final": np.ones(D, np.float32),
        "wcls": w(D, cfg.vocab_size),
        "layers": layers,
    }


def device_random_params(
    cfg: ModelConfig, seed: int = 0, dtype=None, scale: float = 0.02, mesh=None
) -> dict:
    """Random params generated ON DEVICE (one jitted program) — a 7B bf16
    pytree never exists in host RAM. With ``mesh``, the program writes each
    tensor directly into its TP sharding, so no chip ever holds the full
    model. For benchmarks and dry-runs."""
    dtype = dtype or cfg.jax_dtype
    L, D, H, KV = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.kv_dim

    shapes = {
        "embedding": ((cfg.vocab_size, D), jnp.float32),
        "rms_final": ((D,), jnp.float32),
        "wcls": ((D, cfg.vocab_size), dtype),
        "layers": {
            "wq": ((L, D, D), dtype),
            "wk": ((L, D, KV), dtype),
            "wv": ((L, D, KV), dtype),
            "wo": ((L, D, D), dtype),
            "rms_att": ((L, D), jnp.float32),
            "rms_ffn": ((L, D), jnp.float32),
        },
    }
    if cfg.is_moe:
        E = cfg.n_experts
        shapes["layers"].update(
            moe_router=((L, D, E), jnp.float32),
            moe_up=((L, E, D, H), dtype),
            moe_gate=((L, E, D, H), dtype),
            moe_down=((L, E, H, D), dtype),
        )
        if cfg.post_norms:
            shapes["layers"]["rms_moe"] = ((L, D), jnp.float32)
            shapes["layers"]["rms_ffn2"] = ((L, D), jnp.float32)
    else:
        shapes["layers"].update(
            w1=((L, D, H), dtype), w2=((L, H, D), dtype), w3=((L, D, H), dtype)
        )

    def init(key):
        leaves, treedef = jax.tree.flatten(shapes, is_leaf=lambda x: isinstance(x, tuple) and isinstance(x[0], tuple))
        keys = jax.random.split(key, len(leaves))
        out = []
        for k, (shape, dt) in zip(keys, leaves):
            # generate directly in the target dtype: an f32 intermediate for a
            # stacked-layer 7B tensor is a multi-GB transient that OOMs a chip
            out.append(jax.random.normal(k, shape, dt) * jnp.asarray(scale, dt))
        return jax.tree.unflatten(treedef, out)

    if mesh is not None:
        from jax.sharding import NamedSharding

        from dllama_tpu.parallel.mesh import TP
        from dllama_tpu.parallel.sharding import param_specs

        specs = param_specs(cfg, mesh.shape[TP])
        out_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
        init_fn = jax.jit(init, out_shardings=out_shardings)
    else:
        init_fn = jax.jit(init)
    params = init_fn(jax.random.PRNGKey(seed))
    # norms start at 1 like a real checkpoint
    params["rms_final"] = jnp.ones_like(params["rms_final"])
    for name in ("rms_att", "rms_ffn", "rms_moe", "rms_ffn2"):
        if name in params["layers"]:
            params["layers"][name] = jnp.ones_like(params["layers"][name])
    return params


def init_cache(cfg: ModelConfig, cache_dtype=jnp.float32) -> dict:
    """Fixed-size per-layer KV cache [L, seq_len, n_kv_heads, head_size]."""
    shape = (cfg.n_layers, cfg.seq_len, cfg.n_kv_heads, cfg.head_size)
    return {"k": jnp.zeros(shape, cache_dtype), "v": jnp.zeros(shape, cache_dtype)}


def rope_tables(cfg: ModelConfig) -> dict:
    cos, sin = rope_table(cfg.seq_len, cfg.head_size, cfg.rope_theta)
    return {"cos": jnp.asarray(cos), "sin": jnp.asarray(sin)}


# ---------------------------------------------------------------------------
# Forward pass
# ---------------------------------------------------------------------------

def _norm_proj(x, norm_w, w, layer, eps):
    """``rmsnorm(x, norm_w) @ w``. With DLLAMA_FUSE_NORM and a quantized
    ``w``, the norm rides inside the matmul kernel as an x-block epilogue
    (qmatmul.qmatmul_norm — bit-identical, one fewer activation HBM
    round-trip). Callers needing the same normalized activation for several
    projections call this per projection: fused, the epilogue recomputes
    in-register (the point); unfused, XLA CSEs the repeated rmsnorm."""
    if norm_fusion_engages(w):
        return qmatmul_norm(x, norm_w, w, layer, eps)
    return matmul_any(rmsnorm(x, norm_w, eps), w, layer)


def _check_tp_reduce(cfg: ModelConfig, tp_reduce) -> bool:
    """Static validation of the row-parallel reduce mode; True when active.

    MoE is rejected at trace time with the same machine-visible style as
    ``_check_overlap_split``: the expert stacks keep output-axis shards
    (every device holds a slice of EVERY expert), so there is no K-sharded
    down-projection to feed partials from."""
    if tp_reduce is None:
        return False
    if tp_reduce not in ("plain", "q80"):
        raise ValueError(f"tp_reduce must be None, 'plain' or 'q80', "
                         f"got {tp_reduce!r}")
    if cfg.is_moe:
        raise ValueError(
            "tp_reduce requires a dense FFN: MoE expert stacks shard their "
            "output axis (a slice of every expert per device), so no "
            "row-parallel down-projection exists to produce partial sums")
    return True


def _row_norm_gather(x_s: jnp.ndarray, norm_w, tp_axis, tp_compress: bool,
                     eps: float, full_dim: int) -> jnp.ndarray:
    """The fused norm+reduce epilogue's gather half: rmsnorm the SCATTERED
    residual ``[..., dim/tp]`` (one scalar psum for the mean-square, see
    ``collectives.rms_inv_scattered``) and all-gather the normalized rows.
    The full-width gather that the un-fused path would spend reassembling
    the raw residual is gone — the one gather per sub-block now carries the
    next matmul's already-normalized input. Mirrors ``ops.norms.rmsnorm``'s
    f32 accumulation and ``w * (x * inv)`` ordering."""
    inv = _rms_inv(x_s, tp_axis, full_dim, eps)
    xn = _gather((x_s.astype(jnp.float32) * inv[..., None]).astype(x_s.dtype),
                 tp_axis, tp_compress)
    return (norm_w.astype(jnp.float32) * xn.astype(jnp.float32)
            ).astype(x_s.dtype)


def _dense_ffn_row(cfg: ModelConfig, lp: dict, xn: jnp.ndarray,
                   layer=None) -> jnp.ndarray:
    """Row-parallel FFN half on the ALREADY-NORMALIZED full-width input:
    w1/w3 emit their local output shards, which feed the K-sharded w2
    directly — no hidden-width gather at all (the row-parallel point: the
    gathered hidden is ~2.7x dim for 7B). Returns [T, dim] f32 PARTIAL sums
    for the caller's ring reduce-scatter. ``lp['w2']`` is a
    ``row_shard_quant_leaf`` repack whose ``k_logical`` equals the local
    hidden shard width, so the quant kernel pads the activation to the
    per-shard K itself."""
    act = ACTIVATIONS[cfg.hidden_act]
    h = (act(matmul_any(xn, lp["w1"], layer))
         * matmul_any(xn, lp["w3"], layer))
    return matmul_any(h, lp["w2"], layer).astype(jnp.float32)


def _dense_ffn(cfg: ModelConfig, lp: dict, x: jnp.ndarray, norm_w, tp_axis=None,
               tp_compress: bool = False, layer=None) -> jnp.ndarray:
    """FFN half on the RAW (pre-norm) residual ``x``: the ``rms_ffn`` norm is
    applied via ``_norm_proj`` so it can fuse into the up/gate kernels."""
    act = ACTIVATIONS[cfg.hidden_act]
    eps = cfg.norm_eps
    if "w13" in lp:  # fused single-kernel up|gate projection (fuse_qkv_ffn)
        u = _norm_proj(x, norm_w, lp["w13"], layer, eps)
        half = u.shape[-1] // 2
        h = act(u[..., :half]) * u[..., half:]
        return matmul_any(h, lp["w2"], layer)
    h = (act(_norm_proj(x, norm_w, lp["w1"], layer, eps))
         * _norm_proj(x, norm_w, lp["w3"], layer, eps))
    h = slice_to_in_features(_gather(h, tp_axis, tp_compress), lp["w2"])
    return _gather(matmul_any(h, lp["w2"], layer), tp_axis, tp_compress)


def _ffn_residual(cfg: ModelConfig, lp: dict, x: jnp.ndarray, att_out: jnp.ndarray,
                  tp_axis=None, tp_compress: bool = False, layer=None):
    """Post-attention half of a layer, all three arch variants:

    * llama: ``x += att; x += dense_ffn(rmsnorm(x, rms_ffn))``
      (`/root/reference/src/llama2-tasks.cpp:125-212`)
    * mixtral: same joins with the MoE FFN
      (`/root/reference/src/mixtral-tasks.cpp:24-46`)
    * grok1: the attention output and the MoE output are each rmsnorm'd
      BEFORE their residual adds, with an extra pre-MoE norm:
      ``x += rmsnorm(att, rms_ffn); x += rmsnorm(moe(rmsnorm(x, rms_moe)), rms_ffn2)``
      (`/root/reference/src/grok1-tasks.cpp:16-54,239-262,280-320`)
    """
    from dllama_tpu.models.moe import moe_ffn

    if cfg.is_moe and cfg.post_norms:  # grok1
        x = x + rmsnorm(att_out, lp["rms_ffn"], cfg.norm_eps)
        xb = rmsnorm(x, lp["rms_moe"], cfg.norm_eps)
        return x + rmsnorm(moe_ffn(cfg, lp, xb, layer, tp_axis, tp_compress),
                           lp["rms_ffn2"], cfg.norm_eps)
    x = x + att_out
    if cfg.is_moe:
        xb = rmsnorm(x, lp["rms_ffn"], cfg.norm_eps)
        return x + moe_ffn(cfg, lp, xb, layer, tp_axis, tp_compress)
    return x + _dense_ffn(cfg, lp, x, lp["rms_ffn"], tp_axis, tp_compress,
                          layer)


def _attn_block(cfg: ModelConfig, lp: dict, rope: dict, x, k_cache, v_cache, pos,
                tp_axis=None, tp_compress: bool = False, layer=None,
                row_mode: bool = False):
    """One attention sub-block. Returns (attn output [T, dim], new k/v cache).

    With ``tp_axis`` (inside shard_map, quantized TP): the projections are
    output-sharded, so head counts are *local* — derived from the array
    shapes, never from cfg — and the attention runs on this device's heads
    against its kv-head slice of the cache (the reference's
    ``MultiHeadAttSlice``/``KvCacheSlice`` head split,
    `/root/reference/src/transformer.cpp:161-181`).

    With ``layer`` (the scalar-prefetch scan path): quant matrices in ``lp``
    are layer-stacked and k_cache/v_cache are the FULL [L, S, kv, hd] caches;
    the update touches only (layer, pos..pos+T) and the attention reads the
    layer's slab. Without it, k_cache/v_cache are this layer's [S, kv, hd].

    ``row_mode`` (the --tp-reduce row-parallel path): ``x`` arrives ALREADY
    normalized (the caller's fused norm+gather epilogue), so the projections
    skip ``_norm_proj``; and ``wo`` is K-sharded, so the LOCAL head concat
    feeds it with NO gather and the return value is a full-width f32
    PARTIAL sum for the caller's ring reduce-scatter — both of the attention
    sub-block's gathers disappear."""
    T = x.shape[0]
    eps = cfg.norm_eps

    if row_mode:  # pre-normalized input; rms_att was applied by the caller
        q = matmul_any(x, lp["wq"], layer)
        k = matmul_any(x, lp["wk"], layer)
        v = matmul_any(x, lp["wv"], layer)
    elif "wqkv" in lp:  # fused single-kernel projection (fuse_qkv_ffn; no TP)
        qkv = _norm_proj(x, lp["rms_att"], lp["wqkv"], layer, eps)
        d, kv = cfg.dim, cfg.kv_dim
        q = qkv[:, :d]
        k = qkv[:, d : d + kv]
        v = qkv[:, d + kv :]
    else:
        q = _norm_proj(x, lp["rms_att"], lp["wq"], layer, eps)
        k = _norm_proj(x, lp["rms_att"], lp["wk"], layer, eps)
        v = _norm_proj(x, lp["rms_att"], lp["wv"], layer, eps)
    q = q.reshape(T, -1, cfg.head_size)
    k = k.reshape(T, -1, cfg.head_size)
    v = v.reshape(T, -1, cfg.head_size)

    cos = jax.lax.dynamic_slice_in_dim(rope["cos"], pos, T)[:, None, :]
    sin = jax.lax.dynamic_slice_in_dim(rope["sin"], pos, T)[:, None, :]
    q = apply_rope(q, cos, sin, cfg.rope_style)

    if layer is None:
        k = apply_rope(k, cos, sin, cfg.rope_style)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), pos, axis=0)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), pos, axis=0)
        out = gqa_attention(q, k_cache, v_cache, pos)
    else:
        if fused_rope_cache.engages(T, k_cache.dtype):
            # DLLAMA_FUSE_ROPE_CACHE=1: K rotates in-kernel and lands with V
            # in the stacked cache in one pass (ops.fused_rope_cache) —
            # bit-identical to the apply_rope + dynamic_update_slice below
            k_cache, v_cache = fused_rope_cache.rope_cache_update(
                k, v, cos, sin, k_cache, v_cache, pos, layer, cfg.rope_style)
        else:
            k = apply_rope(k, cos, sin, cfg.rope_style)
            zero = jnp.int32(0)
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype)[None],
                (layer, pos, zero, zero))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype)[None],
                (layer, pos, zero, zero))
        # DLLAMA_FLASH_DECODE=1: online-softmax kernel reading ONLY the live
        # cache prefix, straight from the stacked [L, S, kv, hd] cache — no
        # per-layer slab materialization, bytes scale with pos not seq_len
        # (ops.flash_decode; opt-in until benchmark-proven on hardware).
        # Reached from BOTH engines: the quantized layer-scan and the dense
        # index-scan forward() routes here when the gate engages.
        if flash_decode.engages(T, k_cache.shape[1], k_cache.dtype):
            out = flash_decode.flash_decode_attention(q, k_cache, v_cache, pos, layer)
        else:
            k_slab = jax.lax.dynamic_index_in_dim(k_cache, layer, 0, keepdims=False)
            v_slab = jax.lax.dynamic_index_in_dim(v_cache, layer, 0, keepdims=False)
            out = gqa_attention(q, k_slab, v_slab, pos)
    if row_mode:
        # local heads feed the K-sharded wo directly: no head gather, no
        # output gather — the [T, dim] f32 partial rides the ring reduce
        return (matmul_any(out.reshape(T, -1), lp["wo"], layer)
                .astype(jnp.float32), k_cache, v_cache)
    out = _gather(out.reshape(T, -1), tp_axis, tp_compress)  # local heads -> full
    return _gather(matmul_any(out, lp["wo"], layer), tp_axis, tp_compress), k_cache, v_cache


def forward(
    cfg: ModelConfig,
    params: dict,
    rope: dict,
    tokens: jnp.ndarray,  # [T] int32
    cache: dict,  # {"k","v": [L, S, n_kv, hd]}
    pos,  # scalar int32: sequence position of tokens[0]
    tp_axis: str | None = None,
    gather_logits: bool = True,
    tp_compress: bool = False,
    allow_flash: bool = True,
    last_pos=None,
    tp_reduce=None,
) -> tuple:
    """Process T tokens starting at ``pos``. Returns (logits [T, vocab] f32, new cache).

    T==1 is the decode step; larger T is batched prefill (the reference feeds
    prompt tokens one at a time — batching them is the first TPU win).

    ``tp_axis``: when called inside shard_map over a tp mesh axis (the
    quantized-TP path, parallel.quant_tp), params/cache are local shards and
    activations are re-gathered after each output-sharded matmul. With
    ``gather_logits=False`` the classifier is replicated (vocab not divisible
    by tp) and the final gather is skipped.

    ``allow_flash=False``: the caller runs this forward under pjit with
    sharded dense params (runtime.generate's dense-mesh path). GSPMD cannot
    partition a Pallas custom call, so routing into the flash kernel there
    would compile it replicated against an all-gathered cache — the caller
    must pin the dense xs-scan instead.

    ``last_pos`` (traced scalar): compute the lm_head only at that row —
    logits come back [1, vocab]. Prefill reads exactly one row of logits,
    and at a 128k vocab the [bucket, vocab] classifier matmul dwarfs the
    one row actually consumed; every layer still processes (and caches) all
    T positions.

    ``tp_reduce`` (None | 'plain' | 'q80'): the row-parallel reduce path —
    wo/w2 are K-sharded (``quant_tp.row_shard_quant_leaf`` repacks), the
    residual rides the layer scan SCATTERED to [T, dim/tp], each sub-block's
    partial sums take a ring reduce-scatter (Q80-compressed hops when
    'q80'), and the fused norm+reduce epilogue folds residual-add + rmsnorm
    into the scattered shard so the one gather per sub-block carries the
    next matmul's already-normalized input. Quantized shard_map path only.
    """
    x = embed(cfg, params, tokens)
    layers = params["layers"]
    quant_scan = any(isinstance(v, QuantTensor) for v in layers.values())
    # row mode needs the quantized index-scan (row_shard_quant_leaf repacks
    # quant planes; the Engine declines it elsewhere)
    row = (_check_tp_reduce(cfg, tp_reduce) and tp_axis is not None
           and quant_scan)
    red_compress = tp_reduce == "q80"
    # Dense weights normally scan the layer stack as scan-xs (per-layer
    # slabs); when flash decode engages, take the index-scan instead so the
    # stacked KV cache rides the carry and the flash kernel reads its live
    # prefix in place — dense weight slices still fuse into the dots (a
    # dense dynamic-slice is fusable, unlike a Pallas operand).
    if quant_scan or (allow_flash and flash_decode.engages(
            tokens.shape[0], cache["k"].shape[1], cache["k"].dtype)):
        # Scan over a layer INDEX with the stacked quant planes closed over
        # as scan constants. Slicing the planes in the body (`w[idx]`) would
        # make XLA materialize a full copy of every layer's weights each
        # step (a Pallas custom-call operand can't fuse a dynamic-slice) —
        # ~3x the per-token HBM traffic of reading the weights once. Instead
        # a scalar-prefetched idx steers each kernel's own DMA straight into
        # the stacked plane (qmatmul.*_stacked) and the KV cache is updated
        # in place at (idx, pos).
        if row:
            x = _scatter(x, tp_axis)  # residual rides the scan scattered

        def layer_step(carry, idx):
            x, k_cache, v_cache = carry
            lp = {
                name: (leaf if isinstance(leaf, QuantTensor)
                       else jax.lax.dynamic_index_in_dim(leaf, idx, 0, keepdims=False))
                for name, leaf in layers.items()
            }
            if row:
                xn = _row_norm_gather(x, lp["rms_att"], tp_axis, tp_compress,
                                      cfg.norm_eps, cfg.dim)
                att_p, k_cache, v_cache = _attn_block(
                    cfg, lp, rope, xn, k_cache, v_cache, pos, tp_axis,
                    tp_compress, layer=idx, row_mode=True)
                x = x + _reduce_scatter(att_p, tp_axis,
                                        red_compress).astype(x.dtype)
                xn = _row_norm_gather(x, lp["rms_ffn"], tp_axis, tp_compress,
                                      cfg.norm_eps, cfg.dim)
                ffn_p = _dense_ffn_row(cfg, lp, xn, layer=idx)
                x = x + _reduce_scatter(ffn_p, tp_axis,
                                        red_compress).astype(x.dtype)
                return (x, k_cache, v_cache), None
            att_out, k_cache, v_cache = _attn_block(
                cfg, lp, rope, x, k_cache, v_cache, pos, tp_axis, tp_compress,
                layer=idx,
            )
            x = _ffn_residual(cfg, lp, x, att_out, tp_axis, tp_compress, layer=idx)
            return (x, k_cache, v_cache), None

        (x, new_k, new_v), _ = jax.lax.scan(
            layer_step, (x, cache["k"], cache["v"]),
            jnp.arange(cfg.n_layers, dtype=jnp.int32),
        )
    else:
        def layer_step(x, layer):
            lp, k_cache, v_cache = layer
            att_out, k_cache, v_cache = _attn_block(
                cfg, lp, rope, x, k_cache, v_cache, pos, tp_axis, tp_compress
            )
            x = _ffn_residual(cfg, lp, x, att_out, tp_axis, tp_compress)
            return x, (k_cache, v_cache)

        x, (new_k, new_v) = jax.lax.scan(
            layer_step, x, (layers, cache["k"], cache["v"])
        )

    if last_pos is not None:
        x = jax.lax.dynamic_slice_in_dim(x, last_pos, 1, axis=0)
    if row:
        # one last fused norm+gather reassembles the scattered residual
        # already normalized for the classifier
        x = _row_norm_gather(x, params["rms_final"], tp_axis, tp_compress,
                             cfg.norm_eps, cfg.dim)
    else:
        x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = matmul_any(x, params["wcls"]).astype(jnp.float32)
    if tp_axis is not None and gather_logits:
        # slice off any lane-alignment vocab padding (zero logits there would
        # beat real negative logits in an argmax) — no-op when unpadded
        logits = _gather(logits, tp_axis)[..., : cfg.vocab_size]
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits, {"k": new_k, "v": new_v}


def init_batch_cache(cfg: ModelConfig, batch: int, cache_dtype=jnp.float32,
                     seq_len: int = None) -> dict:
    """KV cache for ``batch`` independent sequences: [L, B, S, kv, hd].

    ``seq_len`` overrides the context length of the slab (default
    ``cfg.seq_len``) — the bucketed slot pools allocate short-context slabs
    for short rows; attention masks by ``pos``, so a slab shorter than the
    model context is exact as long as every row's pos stays inside it."""
    S = cfg.seq_len if seq_len is None else seq_len
    shape = (cfg.n_layers, batch, S, cfg.n_kv_heads, cfg.head_size)
    return {"k": jnp.zeros(shape, cache_dtype), "v": jnp.zeros(shape, cache_dtype)}


def _attn_block_batched(cfg: ModelConfig, lp: dict, rope: dict, x, k_cache,
                        v_cache, pos, layer=None, tp_axis=None,
                        tp_compress: bool = False, row_mode: bool = False):
    """Batched-decode attention: x [B, dim] carries B INDEPENDENT sequences,
    each at its own position pos[b]. The projections are ordinary [B, K]
    matmuls (identical to a T=B prefill row block — the quant kernels need
    no batching rule); only rope/cache/attention are per-row, via gather and
    vmap over the pure-jnp attention. Caches are [L, B, S, kv, hd] under the
    layer scan (``layer`` given) or this layer's [B, S, kv, hd] slab.
    ``tp_axis`` (inside shard_map): local heads + kv-shard cache, activation
    gathers after the head concat and the wo matmul, exactly `_attn_block`.
    ``row_mode``: pre-normalized input, K-sharded wo, f32 partial output —
    see ``_attn_block``."""
    B = x.shape[0]
    eps = cfg.norm_eps
    if row_mode:  # pre-normalized input; rms_att was applied by the caller
        q = matmul_any(x, lp["wq"], layer)
        k = matmul_any(x, lp["wk"], layer)
        v = matmul_any(x, lp["wv"], layer)
    elif "wqkv" in lp:
        qkv = _norm_proj(x, lp["rms_att"], lp["wqkv"], layer, eps)
        d, kv = cfg.dim, cfg.kv_dim
        q, k, v = qkv[:, :d], qkv[:, d : d + kv], qkv[:, d + kv :]
    else:
        q = _norm_proj(x, lp["rms_att"], lp["wq"], layer, eps)
        k = _norm_proj(x, lp["rms_att"], lp["wk"], layer, eps)
        v = _norm_proj(x, lp["rms_att"], lp["wv"], layer, eps)
    q = q.reshape(B, -1, cfg.head_size)
    k = k.reshape(B, -1, cfg.head_size)
    v = v.reshape(B, -1, cfg.head_size)

    cos = rope["cos"][pos][:, None, :]  # per-row angle: [B, 1, hs/2]
    sin = rope["sin"][pos][:, None, :]
    q = apply_rope(q, cos, sin, cfg.rope_style)

    fused_kv = (layer is not None
                and fused_rope_cache.engages(1, k_cache.dtype))
    if fused_kv:
        # DLLAMA_FUSE_ROPE_CACHE=1: rotate each row's K in-kernel and land
        # K/V at (layer, b, pos[b]) in one pass — bit-identical to the
        # scatter/DUS writes below, including their end-of-sequence clamp
        k_cache, v_cache = fused_rope_cache.rope_cache_update_batched(
            k, v, cos, sin, k_cache, v_cache, pos, layer, cfg.rope_style)
    else:
        k = apply_rope(k, cos, sin, cfg.rope_style)

    if (layer is not None
            and flash_decode.engages(1, k_cache.shape[2], k_cache.dtype)):
        # flash path: scatter this step's K/V straight into the stacked
        # [L, B, S, kv, hd] cache (no slab round-trip at all) and read each
        # row's OWN live prefix in the kernel. The write position clamps to
        # the last slot so a row stepped at pos >= seq_len leaves the same
        # cache contents as the dense path's dynamic_update_slice (which
        # clamps), instead of the scatter silently dropping the row.
        if not fused_kv:
            rows = jnp.arange(B, dtype=jnp.int32)
            wpos = jnp.clip(pos, 0, k_cache.shape[2] - 1)
            k_cache = k_cache.at[layer, rows, wpos].set(k.astype(k_cache.dtype))
            v_cache = v_cache.at[layer, rows, wpos].set(v.astype(v_cache.dtype))
        out = flash_decode.flash_decode_attention_batched(
            q, k_cache, v_cache, pos, layer)  # [B, local heads, hs]
    else:
        if layer is None:
            slab_k, slab_v = k_cache, v_cache
        else:
            slab_k = jax.lax.dynamic_index_in_dim(k_cache, layer, 0, keepdims=False)
            slab_v = jax.lax.dynamic_index_in_dim(v_cache, layer, 0, keepdims=False)
        if not fused_kv:
            write = jax.vmap(
                lambda c, kk, p: jax.lax.dynamic_update_slice_in_dim(
                    c, kk[None].astype(c.dtype), p, axis=0))
            slab_k = write(slab_k, k, pos)
            slab_v = write(slab_v, v, pos)
            if layer is None:
                k_cache, v_cache = slab_k, slab_v
            else:
                zero = (0, 0, 0, 0)
                k_cache = jax.lax.dynamic_update_slice(k_cache, slab_k[None], (layer, *zero))
                v_cache = jax.lax.dynamic_update_slice(v_cache, slab_v[None], (layer, *zero))

        out = jax.vmap(
            lambda qb, ks, vs, p: gqa_attention(qb[None], ks, vs, p)[0]
        )(q, slab_k, slab_v, pos)  # [B, local heads, hs]
    if row_mode:  # local heads -> K-sharded wo: no gathers, f32 partials
        return (matmul_any(out.reshape(B, -1), lp["wo"], layer)
                .astype(jnp.float32), k_cache, v_cache)
    out = _gather(out.reshape(B, -1), tp_axis, tp_compress)
    return (_gather(matmul_any(out, lp["wo"], layer), tp_axis, tp_compress),
            k_cache, v_cache)


def forward_batched(
    cfg: ModelConfig,
    params: dict,
    rope: dict,
    tokens: jnp.ndarray,  # [B] int32 — one pending token per sequence
    cache: dict,  # {"k","v": [L, B, S, n_kv, hd]}
    pos: jnp.ndarray,  # [B] int32 — each sequence's own position
    tp_axis: str | None = None,
    gather_logits: bool = True,
    tp_compress: bool = False,
    allow_flash: bool = True,
    tp_reduce=None,
) -> tuple:
    """One decode step for B independent sequences -> (logits [B, vocab], cache).

    The TPU throughput move the reference's batch=1 design cannot make
    (`/root/reference/src/tasks.cpp:199-210`): decode is weight-bandwidth
    bound, and the [B, K] activation streams every weight from HBM ONCE for
    all B sequences — ~B x aggregate tokens/s at nearly the single-stream
    step latency. Row b's math is exactly ``forward`` at T=1, pos[b]
    (greedy-tested per row); MoE routing/union selection is per-row already.
    ``tp_axis``: inside shard_map over a tp mesh (quant-TP batched serving,
    parallel.quant_tp.make_tp_forward_batched) — same gathers as ``forward``.
    ``allow_flash=False``: caller runs under pjit with sharded dense params
    (see ``forward``) — pin the dense xs-scan.
    ``tp_reduce``: the row-parallel wo/w2 reduce path, see ``forward``.
    """
    x = embed(cfg, params, tokens)
    layers = params["layers"]
    quant_scan = any(isinstance(v, QuantTensor) for v in layers.values())
    row = (_check_tp_reduce(cfg, tp_reduce) and tp_axis is not None
           and quant_scan)
    red_compress = tp_reduce == "q80"
    # same routing as `forward`: dense weights take the index-scan when the
    # batched flash kernel engages, so the stacked [L, B, S, kv, hd] cache
    # stays in the carry and each row reads only its own live prefix
    if quant_scan or (allow_flash and flash_decode.engages(
            1, cache["k"].shape[2], cache["k"].dtype)):
        if row:
            x = _scatter(x, tp_axis)  # residual rides the scan scattered

        def layer_step(carry, idx):
            x, k_cache, v_cache = carry
            lp = {
                name: (leaf if isinstance(leaf, QuantTensor)
                       else jax.lax.dynamic_index_in_dim(leaf, idx, 0, keepdims=False))
                for name, leaf in layers.items()
            }
            if row:
                xn = _row_norm_gather(x, lp["rms_att"], tp_axis, tp_compress,
                                      cfg.norm_eps, cfg.dim)
                att_p, k_cache, v_cache = _attn_block_batched(
                    cfg, lp, rope, xn, k_cache, v_cache, pos, layer=idx,
                    tp_axis=tp_axis, tp_compress=tp_compress, row_mode=True)
                x = x + _reduce_scatter(att_p, tp_axis,
                                        red_compress).astype(x.dtype)
                xn = _row_norm_gather(x, lp["rms_ffn"], tp_axis, tp_compress,
                                      cfg.norm_eps, cfg.dim)
                ffn_p = _dense_ffn_row(cfg, lp, xn, layer=idx)
                x = x + _reduce_scatter(ffn_p, tp_axis,
                                        red_compress).astype(x.dtype)
                return (x, k_cache, v_cache), None
            att_out, k_cache, v_cache = _attn_block_batched(
                cfg, lp, rope, x, k_cache, v_cache, pos, layer=idx,
                tp_axis=tp_axis, tp_compress=tp_compress)
            x = _ffn_residual(cfg, lp, x, att_out, tp_axis, tp_compress, layer=idx)
            return (x, k_cache, v_cache), None

        (x, new_k, new_v), _ = jax.lax.scan(
            layer_step, (x, cache["k"], cache["v"]),
            jnp.arange(cfg.n_layers, dtype=jnp.int32),
        )
    else:
        def layer_step(x, layer):
            lp, k_cache, v_cache = layer
            att_out, k_cache, v_cache = _attn_block_batched(
                cfg, lp, rope, x, k_cache, v_cache, pos,
                tp_axis=tp_axis, tp_compress=tp_compress)
            x = _ffn_residual(cfg, lp, x, att_out, tp_axis, tp_compress)
            return x, (k_cache, v_cache)

        x, (new_k, new_v) = jax.lax.scan(
            layer_step, x, (layers, cache["k"], cache["v"])
        )
    if row:
        x = _row_norm_gather(x, params["rms_final"], tp_axis, tp_compress,
                             cfg.norm_eps, cfg.dim)
    else:
        x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = matmul_any(x, params["wcls"]).astype(jnp.float32)
    if tp_axis is not None and gather_logits:
        # slice off lane-alignment vocab padding, exactly like `forward`
        logits = _gather(logits, tp_axis)[..., : cfg.vocab_size]
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits, {"k": new_k, "v": new_v}


def _overlap_axis(tp_axis, ring: bool):
    from dllama_tpu.parallel.collectives import RingAxis

    return RingAxis(tp_axis) if (ring and tp_axis is not None) else tp_axis


def _check_overlap_split(cfg: ModelConfig, batch: int) -> int:
    """Static validation of the two-microbatch split; returns the cut row.

    MoE is rejected at trace time: ``_moe_decode_selected`` computes the
    selected-experts union over ALL rows (cap ``min(E, T*k)`` from the
    column maxima), so a row-split changes which experts run and the
    result would not be bit-identical to the monolithic step."""
    if cfg.is_moe:
        raise ValueError(
            "tp_overlap requires a dense FFN: the MoE selected-experts "
            "union spans all rows, so a microbatch split changes the "
            "expert schedule (not bit-identical)")
    if batch < 2:
        raise ValueError(f"tp_overlap needs batch >= 2 rows, got {batch}")
    return batch // 2


def forward_batched_overlap(
    cfg: ModelConfig,
    params: dict,
    rope: dict,
    tokens: jnp.ndarray,  # [B] int32 — one pending token per sequence
    cache: dict,  # {"k","v": [L, B, S, n_kv, hd]}
    pos: jnp.ndarray,  # [B] int32 — each sequence's own position
    tp_axis: str | None = None,
    gather_logits: bool = True,
    tp_compress: bool = False,
    allow_flash: bool = True,
    ring: bool = True,
    tp_reduce=None,
) -> tuple:
    """``forward_batched`` with the rows split into two microbatches whose
    per-layer schedules interleave — the TokenWeave-style compute/comm
    overlap for TP decode, EXACT by construction.

    Per layer, microbatch A's attention (ending in its head + wo gathers)
    is issued before microbatch B's in program order; the two chains share
    only the layer's weights (read-only), so XLA's latency-hiding
    scheduler is free to run B's matmuls while A's gather is on the wire.
    With ``ring=True`` each gather is the ``lax.ppermute`` chunk rotation
    (`parallel.collectives.RingAxis`): tp-1 small async hops instead of
    one fused blocking all-gather, giving the scheduler hop-granular
    boundaries to hide. ``ring=False`` keeps fused all-gathers and relies
    on XLA alone over the interleaved two-microbatch HLO.

    Bit-identity with the monolithic step (tested across tp degrees with
    and without ``tp_compress``): every op in the layer body is per-row
    (rmsnorm, rope, cache write, attention, sampling upstream), the
    matmuls compute each output row from the full K independent of the
    other rows, and the gathered chunk concatenation order is fixed —
    so splitting [B] into [B//2] + [B - B//2] permutes nothing. Both
    halves advance inside ONE layer scan, so weights still stream from
    HBM once per layer for all B rows. MoE is rejected (see
    ``_check_overlap_split``).

    ``tp_reduce`` composes: each microbatch runs the row-parallel sequence
    (fused norm+gather, K-sharded wo/w2, ring reduce-scatter) with the SAME
    interleaving — the reduce-scatters are tp-1 ppermute hops by
    construction, so they give the scheduler the same hop-granular
    boundaries the ring gathers do. Row mode is NOT bit-identical to the
    monolithic gather path (split-K reassociation); it IS the same math as
    the non-overlap row-parallel step, microbatch-split exactly."""
    B = tokens.shape[0]
    h = _check_overlap_split(cfg, B)
    ga = _overlap_axis(tp_axis, ring)
    x = embed(cfg, params, tokens)
    xa, xb = x[:h], x[h:]
    pa, pb = pos[:h], pos[h:]
    ka, kb = cache["k"][:, :h], cache["k"][:, h:]
    va, vb = cache["v"][:, :h], cache["v"][:, h:]
    layers = params["layers"]
    quant_scan = any(isinstance(v, QuantTensor) for v in layers.values())
    row = (_check_tp_reduce(cfg, tp_reduce) and tp_axis is not None
           and quant_scan)
    red_compress = tp_reduce == "q80"
    if row:
        xa, xb = _scatter(xa, ga), _scatter(xb, ga)

    def _row_half(lp, idx, x_s, kc, vc, p):
        """One microbatch's row-parallel layer: fused norm+gather feeds the
        attention, partials ride the ring, residual adds stay scattered."""
        xn = _row_norm_gather(x_s, lp["rms_att"], ga, tp_compress,
                              cfg.norm_eps, cfg.dim)
        att_p, kc, vc = _attn_block_batched(
            cfg, lp, rope, xn, kc, vc, p, layer=idx,
            tp_axis=ga, tp_compress=tp_compress, row_mode=True)
        x_s = x_s + _reduce_scatter(att_p, ga, red_compress).astype(x_s.dtype)
        xn = _row_norm_gather(x_s, lp["rms_ffn"], ga, tp_compress,
                              cfg.norm_eps, cfg.dim)
        ffn_p = _dense_ffn_row(cfg, lp, xn, layer=idx)
        x_s = x_s + _reduce_scatter(ffn_p, ga, red_compress).astype(x_s.dtype)
        return x_s, kc, vc

    def layer_step(carry, idx):
        xa, xb, ka, kb, va, vb = carry
        lp = {
            name: (leaf if isinstance(leaf, QuantTensor)
                   else jax.lax.dynamic_index_in_dim(leaf, idx, 0, keepdims=False))
            for name, leaf in layers.items()
        }
        if row:
            xa, ka, va = _row_half(lp, idx, xa, ka, va, pa)
            xb, kb, vb = _row_half(lp, idx, xb, kb, vb, pb)
            return (xa, xb, ka, kb, va, vb), None
        att_a, ka, va = _attn_block_batched(
            cfg, lp, rope, xa, ka, va, pa, layer=idx,
            tp_axis=ga, tp_compress=tp_compress)
        att_b, kb, vb = _attn_block_batched(
            cfg, lp, rope, xb, kb, vb, pb, layer=idx,
            tp_axis=ga, tp_compress=tp_compress)
        xa = _ffn_residual(cfg, lp, xa, att_a, ga, tp_compress, layer=idx)
        xb = _ffn_residual(cfg, lp, xb, att_b, ga, tp_compress, layer=idx)
        return (xa, xb, ka, kb, va, vb), None

    (xa, xb, ka, kb, va, vb), _ = jax.lax.scan(
        layer_step, (xa, xb, ka, kb, va, vb),
        jnp.arange(cfg.n_layers, dtype=jnp.int32),
    )
    if row:  # per-half fused final norm (rmsnorm is per-row, so exact)
        xa = _row_norm_gather(xa, params["rms_final"], ga, tp_compress,
                              cfg.norm_eps, cfg.dim)
        xb = _row_norm_gather(xb, params["rms_final"], ga, tp_compress,
                              cfg.norm_eps, cfg.dim)
    # rejoin, then a tail IDENTICAL to forward_batched's: the final rmsnorm,
    # logits matmul and (plain fused) logits gather see the same [B, dim]
    x = jnp.concatenate([xa, xb], axis=0)
    new_k = jnp.concatenate([ka, kb], axis=1)
    new_v = jnp.concatenate([va, vb], axis=1)
    if not row:
        x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = matmul_any(x, params["wcls"]).astype(jnp.float32)
    if tp_axis is not None and gather_logits:
        logits = _gather(logits, tp_axis)[..., : cfg.vocab_size]
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits, {"k": new_k, "v": new_v}


def _verify_layer(cfg: ModelConfig, lp: dict, rope: dict, x, k_cache,
                  v_cache, pos, idx, tp_axis=None, tp_compress: bool = False,
                  row_mode: bool = False, red_compress: bool = False):
    """One layer of the batched spec-verify step: x [B, T, dim], stacked
    [L, B, S, kv, hd] caches, per-row base positions ``pos``. The shared
    body of ``forward_batched_verify`` and its microbatch-overlap twin.

    ``row_mode`` (--tp-reduce): ``x`` arrives SCATTERED ``[B, T, dim/tp]``
    and stays scattered on return — the fused norm+gather feeds the
    projections, the K-sharded ``wo``/``w2`` partials ride the ring
    reduce-scatter, and the residual adds happen on the shard."""
    B, T = x.shape[:2]
    if row_mode:
        x_s = x.reshape(B * T, x.shape[-1])  # scattered residual rows
        xn = _row_norm_gather(x_s, lp["rms_att"], tp_axis, tp_compress,
                              cfg.norm_eps, cfg.dim)
        q = matmul_any(xn, lp["wq"], idx)
        k = matmul_any(xn, lp["wk"], idx)
        v = matmul_any(xn, lp["wv"], idx)
    elif "wqkv" in lp:
        xf = x.reshape(B * T, cfg.dim)  # raw rows; rmsnorm rides in _norm_proj
        qkv = _norm_proj(xf, lp["rms_att"], lp["wqkv"], idx, cfg.norm_eps)
        d, kv = cfg.dim, cfg.kv_dim
        q, k, v = qkv[:, :d], qkv[:, d : d + kv], qkv[:, d + kv :]
    else:
        xf = x.reshape(B * T, cfg.dim)  # raw rows; rmsnorm rides in _norm_proj
        q = _norm_proj(xf, lp["rms_att"], lp["wq"], idx, cfg.norm_eps)
        k = _norm_proj(xf, lp["rms_att"], lp["wk"], idx, cfg.norm_eps)
        v = _norm_proj(xf, lp["rms_att"], lp["wv"], idx, cfg.norm_eps)
    # head counts derive from the ARRAY shapes: under tp they are the
    # local slices (the reference's MultiHeadAttSlice head split)
    q = q.reshape(B, T, -1, cfg.head_size)
    k = k.reshape(B, T, -1, cfg.head_size)
    v = v.reshape(B, T, -1, cfg.head_size)

    # per-row angles for positions pos[b]..pos[b]+T-1 (the table gather
    # clamps at seq_len-1; rows that close are emission-capped by the
    # caller's budgets before any clamped position could be emitted)
    ppos = pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    cos = rope["cos"][ppos][:, :, None, :]  # [B, T, 1, hs/2]
    sin = rope["sin"][ppos][:, :, None, :]
    q = apply_rope(q, cos, sin, cfg.rope_style)

    if fused_rope_cache.engages(T, k_cache.dtype):
        # DLLAMA_FUSE_ROPE_CACHE=1: rotate the draft rows' K in-kernel and
        # land K/V at (idx, b, pos[b]..pos[b]+T) in one pass — bit-identical
        # to the apply_rope + per-row slab writes below
        k_cache, v_cache = fused_rope_cache.rope_cache_update_verify(
            k, v, cos, sin, k_cache, v_cache, pos, idx, cfg.rope_style)
        slab_k = jax.lax.dynamic_index_in_dim(k_cache, idx, 0, keepdims=False)
        slab_v = jax.lax.dynamic_index_in_dim(v_cache, idx, 0, keepdims=False)
    else:
        k = apply_rope(k, cos, sin, cfg.rope_style)
        slab_k = jax.lax.dynamic_index_in_dim(k_cache, idx, 0, keepdims=False)
        slab_v = jax.lax.dynamic_index_in_dim(v_cache, idx, 0, keepdims=False)
        write = jax.vmap(
            lambda c, kk, p: jax.lax.dynamic_update_slice_in_dim(
                c, kk.astype(c.dtype), p, axis=0))
        slab_k = write(slab_k, k, pos)
        slab_v = write(slab_v, v, pos)
        zero = (0, 0, 0, 0)
        k_cache = jax.lax.dynamic_update_slice(k_cache, slab_k[None], (idx, *zero))
        v_cache = jax.lax.dynamic_update_slice(v_cache, slab_v[None], (idx, *zero))

    out = jax.vmap(gqa_attention)(q, slab_k, slab_v, pos)  # [B, T, H, hd]
    if row_mode:
        # local heads feed the K-sharded wo directly; the partial rides the
        # ring reduce-scatter and the residual add stays on the shard
        att_p = matmul_any(out.reshape(B * T, -1), lp["wo"], idx
                           ).astype(jnp.float32)
        x_s = x_s + _reduce_scatter(att_p, tp_axis, red_compress
                                    ).astype(x_s.dtype)
        xn = _row_norm_gather(x_s, lp["rms_ffn"], tp_axis, tp_compress,
                              cfg.norm_eps, cfg.dim)
        ffn_p = _dense_ffn_row(cfg, lp, xn, layer=idx)
        x_s = x_s + _reduce_scatter(ffn_p, tp_axis, red_compress
                                    ).astype(x_s.dtype)
        return x_s.reshape(B, T, -1), k_cache, v_cache
    heads = _gather(out.reshape(B * T, -1), tp_axis, tp_compress)
    att = _gather(matmul_any(heads, lp["wo"], idx), tp_axis, tp_compress)
    x = _ffn_residual(cfg, lp, x.reshape(B * T, cfg.dim),
                      att, tp_axis, tp_compress,
                      layer=idx).reshape(B, T, cfg.dim)
    return x, k_cache, v_cache


def forward_batched_verify(
    cfg: ModelConfig,
    params: dict,
    rope: dict,
    tokens: jnp.ndarray,  # [B, T] int32 — pending + draft rows per sequence
    cache: dict,  # {"k","v": [L, B, S, n_kv, hd]}
    pos: jnp.ndarray,  # [B] int32 — position of tokens[b, 0]
    tp_axis: str | None = None,
    gather_logits: bool = True,
    tp_compress: bool = False,
    tp_reduce=None,
) -> tuple:
    """T tokens for each of B independent sequences -> (logits [B, T, vocab]
    f32, cache): the BATCHED speculative-verify step. Row b's math is
    exactly ``forward`` at (T, pos[b]) — T=draft_len+1 candidate positions
    scored in one weight-streaming pass for ALL rows, composing the two
    bandwidth wins (batching shares the weight stream across sequences,
    speculation shares it across positions within each sequence).

    All matmuls run on the flattened [B*T, dim] activation (one kernel call
    per matrix — the quant kernels never see the batch structure); rope,
    cache writes, and attention are per-row (vmap over the pure attention).
    MoE routing on the flattened rows is exact: the selected-experts union
    caps at min(E, B*T*k). Dense attention only (the batched flash kernel
    is one-token-per-row). ``tp_axis``: inside shard_map over a tp mesh
    (quant-TP, parallel.quant_tp.make_tp_verify_batched) — local heads +
    kv-shard caches, the same activation gathers as ``forward_batched``.
    """
    B, T = tokens.shape
    x = embed(cfg, params, tokens)  # [B, T, dim]
    layers = params["layers"]
    quant_scan = any(isinstance(v, QuantTensor) for v in layers.values())
    row = (_check_tp_reduce(cfg, tp_reduce) and tp_axis is not None
           and quant_scan)
    red_compress = tp_reduce == "q80"
    if row:
        x = _scatter(x, tp_axis)

    def layer_step(carry, idx):
        x, k_cache, v_cache = carry
        lp = {
            name: (leaf if isinstance(leaf, QuantTensor)
                   else jax.lax.dynamic_index_in_dim(leaf, idx, 0, keepdims=False))
            for name, leaf in layers.items()
        }
        x, k_cache, v_cache = _verify_layer(
            cfg, lp, rope, x, k_cache, v_cache, pos, idx,
            tp_axis=tp_axis, tp_compress=tp_compress,
            row_mode=row, red_compress=red_compress)
        return (x, k_cache, v_cache), None

    (x, new_k, new_v), _ = jax.lax.scan(
        layer_step, (x, cache["k"], cache["v"]),
        jnp.arange(cfg.n_layers, dtype=jnp.int32),
    )
    if row:  # fused final norm on the scattered residual
        x = _row_norm_gather(x, params["rms_final"], tp_axis, tp_compress,
                             cfg.norm_eps, cfg.dim)
    else:
        x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = matmul_any(x.reshape(B * T, cfg.dim),
                        params["wcls"]).astype(jnp.float32)
    if tp_axis is not None and gather_logits:
        # slice off lane-alignment vocab padding, exactly like `forward`
        logits = _gather(logits, tp_axis)[..., : cfg.vocab_size]
    logits = logits.reshape(B, T, -1)
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits, {"k": new_k, "v": new_v}


def forward_batched_verify_overlap(
    cfg: ModelConfig,
    params: dict,
    rope: dict,
    tokens: jnp.ndarray,  # [B, T] int32 — pending + draft rows per sequence
    cache: dict,  # {"k","v": [L, B, S, n_kv, hd]}
    pos: jnp.ndarray,  # [B] int32 — position of tokens[b, 0]
    tp_axis: str | None = None,
    gather_logits: bool = True,
    tp_compress: bool = False,
    ring: bool = True,
    tp_reduce=None,
) -> tuple:
    """``forward_batched_verify`` with the rows split into two interleaved
    microbatches — the spec-verify twin of ``forward_batched_overlap``
    (same exactness argument: ``_verify_layer`` is per-row throughout, the
    flattened [h*T, dim] matmuls compute each row from the full K, and
    ring-gather chunk order is fixed). Both halves share one layer scan so
    weights stream once per layer. ``tp_reduce`` composes the same way as
    in ``forward_batched_overlap``: each half runs the row-parallel
    ``_verify_layer`` against the ring axis."""
    B, T = tokens.shape
    h = _check_overlap_split(cfg, B)
    ga = _overlap_axis(tp_axis, ring)
    x = embed(cfg, params, tokens)  # [B, T, dim]
    xa, xb = x[:h], x[h:]
    pa, pb = pos[:h], pos[h:]
    ka, kb = cache["k"][:, :h], cache["k"][:, h:]
    va, vb = cache["v"][:, :h], cache["v"][:, h:]
    layers = params["layers"]
    quant_scan = any(isinstance(v, QuantTensor) for v in layers.values())
    row = (_check_tp_reduce(cfg, tp_reduce) and tp_axis is not None
           and quant_scan)
    red_compress = tp_reduce == "q80"
    if row:
        xa, xb = _scatter(xa, ga), _scatter(xb, ga)

    def layer_step(carry, idx):
        xa, xb, ka, kb, va, vb = carry
        lp = {
            name: (leaf if isinstance(leaf, QuantTensor)
                   else jax.lax.dynamic_index_in_dim(leaf, idx, 0, keepdims=False))
            for name, leaf in layers.items()
        }
        xa, ka, va = _verify_layer(cfg, lp, rope, xa, ka, va, pa, idx,
                                   tp_axis=ga, tp_compress=tp_compress,
                                   row_mode=row, red_compress=red_compress)
        xb, kb, vb = _verify_layer(cfg, lp, rope, xb, kb, vb, pb, idx,
                                   tp_axis=ga, tp_compress=tp_compress,
                                   row_mode=row, red_compress=red_compress)
        return (xa, xb, ka, kb, va, vb), None

    (xa, xb, ka, kb, va, vb), _ = jax.lax.scan(
        layer_step, (xa, xb, ka, kb, va, vb),
        jnp.arange(cfg.n_layers, dtype=jnp.int32),
    )
    if row:  # per-half fused final norm (rmsnorm is per-row, so exact)
        xa = _row_norm_gather(xa, params["rms_final"], ga, tp_compress,
                              cfg.norm_eps, cfg.dim)
        xb = _row_norm_gather(xb, params["rms_final"], ga, tp_compress,
                              cfg.norm_eps, cfg.dim)
    x = jnp.concatenate([xa, xb], axis=0)
    new_k = jnp.concatenate([ka, kb], axis=1)
    new_v = jnp.concatenate([va, vb], axis=1)
    if not row:
        x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = matmul_any(x.reshape(B * T, cfg.dim),
                        params["wcls"]).astype(jnp.float32)
    if tp_axis is not None and gather_logits:
        # slice off lane-alignment vocab padding, exactly like `forward`
        logits = _gather(logits, tp_axis)[..., : cfg.vocab_size]
    logits = logits.reshape(B, T, -1)
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits, {"k": new_k, "v": new_v}


def forward_train(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    rope: dict = None,
    mesh=None,
    sp_axis: str = "sp",
) -> jnp.ndarray:
    """Batched cache-free causal forward: tokens [B, T] -> logits [B, T, vocab].

    The inference path above is exact for the reference's decode-only scope;
    this variant exists for the training step (gradients need the whole
    sequence, no cache) and for throughput-style prefill. Same math per
    position — the attention just runs against the in-flight K/V of the same
    sequence instead of a cache.

    Long context: pass a ``mesh`` whose ``sp_axis`` has size > 1 and the
    attention runs as ring attention (``ops.ring_attention``) — each device
    keeps its sequence chunk of K/V, chunks rotate over ICI, per-device
    memory stays O(T / n_sp). Everything else (QKV/FFN matmuls, scan over
    layers) is unchanged; XLA keeps shardings the surrounding pjit chose.
    The sequence axis of ``tokens`` must be sharded over ``sp_axis`` in ring
    order (plain ``P(..., "sp")`` contiguous chunks).
    """
    use_ring = mesh is not None and mesh.shape.get(sp_axis, 1) > 1
    T = tokens.shape[1]
    x = embed(cfg, params, tokens)

    rope_t = rope if rope is not None else rope_tables(cfg)
    cos = rope_t["cos"][:T][None, :, None, :]  # [1, T, 1, hs/2]
    sin = rope_t["sin"][:T][None, :, None, :]

    ring = (mesh, sp_axis) if use_ring else None

    def layer_step(x, lp):
        return train_layer(cfg, lp, cos, sin, x, ring=ring), None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = (x @ params["wcls"]).astype(jnp.float32)
    return logits * cfg.logit_scale if cfg.logit_scale != 1.0 else logits


def embed(cfg: ModelConfig, params: dict, tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding lookup (+ Grok's input scale) in the compute dtype."""
    x = params["embedding"][tokens].astype(cfg.jax_dtype)
    if cfg.embedding_scale != 1.0:
        x = x * jnp.asarray(cfg.embedding_scale, cfg.jax_dtype)
    return x


def train_layer(
    cfg: ModelConfig,
    lp: dict,
    cos: jnp.ndarray,  # [1, T, 1, hs/2]
    sin: jnp.ndarray,
    x: jnp.ndarray,  # [B, T, dim]
    ring=None,  # (mesh, sp_axis) -> ring attention over that axis
) -> jnp.ndarray:
    """One cache-free causal transformer layer (the batched-training twin of
    the incremental ``_attn_block``/``_ffn_residual`` pair). Shared by the
    ``forward_train`` layer scan and the pipeline-parallel stage body."""
    B, T = x.shape[:2]
    group = cfg.n_heads // cfg.n_kv_heads

    xb = rmsnorm(x, lp["rms_att"], cfg.norm_eps)
    q = (xb @ lp["wq"]).reshape(B, T, cfg.n_heads, cfg.head_size)
    k = (xb @ lp["wk"]).reshape(B, T, cfg.n_kv_heads, cfg.head_size)
    v = (xb @ lp["wv"]).reshape(B, T, cfg.n_kv_heads, cfg.head_size)
    q = apply_rope(q, cos, sin, cfg.rope_style)
    k = apply_rope(k, cos, sin, cfg.rope_style)

    if ring is not None:
        from dllama_tpu.ops.ring_attention import ring_self_attention

        mesh, sp_axis = ring
        out = ring_self_attention(q, k, v, mesh, axis_name=sp_axis)
    else:
        causal = jnp.tril(jnp.ones((T, T), bool))
        qf = q.astype(jnp.float32).reshape(B, T, cfg.n_kv_heads, group, cfg.head_size)
        scores = jnp.einsum("btkgh,bskh->bkgts", qf, k.astype(jnp.float32))
        scores = scores / jnp.sqrt(jnp.float32(cfg.head_size))
        scores = jnp.where(causal[None, None, None], scores, jnp.float32(-1e30))
        att = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bkgts,bskh->btkgh", att, v.astype(jnp.float32))
        out = out.astype(x.dtype)
    out = out.reshape(B, T, cfg.dim)
    return _ffn_residual(cfg, lp, x, out @ lp["wo"])
