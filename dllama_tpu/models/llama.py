"""Dense Llama-family transformer — the single-program SPMD forward pass.

Where the reference unrolls 25 root + 15 worker task functions per layer with
explicit broadcast/gather between them (`/root/reference/src/llama2-tasks.cpp:243-300`),
here the whole forward pass is one jitted function: a ``lax.scan`` over stacked
layer parameters, with tensor-parallel sharding expressed as PartitionSpecs
(see ``dllama_tpu.parallel``) so XLA emits the collectives the reference
hand-rolls over TCP.

Math parity notes:
* rmsnorm eps semantics: `/root/reference/src/funcs.cpp:94-123`.
* attention: `/root/reference/src/llama2-tasks.cpp:54-94` (see ops.attention).
* SwiGLU: ``w2( act(w1 x) * (w3 x) )`` — `/root/reference/src/llama2-tasks.cpp:158-189`.
* logits: final rmsnorm then ``wcls`` matmul — `/root/reference/src/llama2-tasks.cpp:222-241`.

Weights use kernel layout ``[in_features, out_features]`` (transposed from the
file's ``[out, in]`` rows) so activations hit the MXU as plain ``x @ w``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.formats.weights import WeightFileReader
from dllama_tpu.models.config import ModelConfig
from dllama_tpu.ops.activations import ACTIVATIONS
from dllama_tpu.ops.attention import gqa_attention
from dllama_tpu.ops.norms import rmsnorm
from dllama_tpu.ops.rope import apply_rope, rope_table


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def params_from_reader(reader: WeightFileReader, cfg: ModelConfig, dtype=None) -> dict:
    """Load `.m` tensors into the stacked-layer pytree (dense archs)."""
    dtype = dtype or cfg.jax_dtype
    p = {
        "embedding": reader.read_tensor("token_embedding", np.float32),
        "rms_final": reader.read_tensor("rms_final", np.float32),
        "wcls": reader.read_tensor("wcls", dtype).T,
    }
    names = ["wq", "wk", "wv", "wo", "w1", "w2", "w3"]
    layers: dict = {n: [] for n in names}
    layers["rms_att"] = []
    layers["rms_ffn"] = []
    for i in range(cfg.n_layers):
        pre = f"layers.{i}."
        for n in names:
            layers[n].append(reader.read_tensor(pre + n, dtype).T)  # [in, out]
        layers["rms_att"].append(reader.read_tensor(pre + "rms_att", np.float32))
        layers["rms_ffn"].append(reader.read_tensor(pre + "rms_ffn", np.float32))
    p["layers"] = {k: np.stack(v) for k, v in layers.items()}
    return p


def random_params(cfg: ModelConfig, seed: int = 0, scale: float = 0.02, dtype=None) -> dict:
    """Seeded synthetic weights (the llama2-tasks-test pattern, for tests/bench)."""
    dtype = dtype or cfg.jax_dtype
    rng = np.random.default_rng(seed)

    def w(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32).astype(dtype)

    L, D, H, KV = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.kv_dim
    return {
        "embedding": w(cfg.vocab_size, D).astype(np.float32),
        "rms_final": np.ones(D, np.float32),
        "wcls": w(D, cfg.vocab_size),
        "layers": {
            "wq": w(L, D, D),
            "wk": w(L, D, KV),
            "wv": w(L, D, KV),
            "wo": w(L, D, D),
            "w1": w(L, D, H),
            "w2": w(L, H, D),
            "w3": w(L, D, H),
            "rms_att": np.ones((L, D), np.float32),
            "rms_ffn": np.ones((L, D), np.float32),
        },
    }


def init_cache(cfg: ModelConfig, cache_dtype=jnp.float32) -> dict:
    """Fixed-size per-layer KV cache [L, seq_len, n_kv_heads, head_size]."""
    shape = (cfg.n_layers, cfg.seq_len, cfg.n_kv_heads, cfg.head_size)
    return {"k": jnp.zeros(shape, cache_dtype), "v": jnp.zeros(shape, cache_dtype)}


def rope_tables(cfg: ModelConfig) -> dict:
    cos, sin = rope_table(cfg.seq_len, cfg.head_size, cfg.rope_theta)
    return {"cos": jnp.asarray(cos), "sin": jnp.asarray(sin)}


# ---------------------------------------------------------------------------
# Forward pass
# ---------------------------------------------------------------------------

def _dense_ffn(cfg: ModelConfig, lp: dict, xb: jnp.ndarray) -> jnp.ndarray:
    act = ACTIVATIONS[cfg.hidden_act]
    h = act(xb @ lp["w1"]) * (xb @ lp["w3"])
    return h @ lp["w2"]


def _attn_block(cfg: ModelConfig, lp: dict, rope: dict, x, k_cache, v_cache, pos):
    """One attention sub-block. Returns (attn output [T, dim], new k/v cache [S,...])."""
    T = x.shape[0]
    xb = rmsnorm(x, lp["rms_att"], cfg.norm_eps)

    q = (xb @ lp["wq"]).reshape(T, cfg.n_heads, cfg.head_size)
    k = (xb @ lp["wk"]).reshape(T, cfg.n_kv_heads, cfg.head_size)
    v = (xb @ lp["wv"]).reshape(T, cfg.n_kv_heads, cfg.head_size)

    cos = jax.lax.dynamic_slice_in_dim(rope["cos"], pos, T)[:, None, :]
    sin = jax.lax.dynamic_slice_in_dim(rope["sin"], pos, T)[:, None, :]
    q = apply_rope(q, cos, sin, cfg.rope_style)
    k = apply_rope(k, cos, sin, cfg.rope_style)

    k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k.astype(k_cache.dtype), pos, axis=0)
    v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v.astype(v_cache.dtype), pos, axis=0)

    out = gqa_attention(q, k_cache, v_cache, pos)
    return out.reshape(T, cfg.dim) @ lp["wo"], k_cache, v_cache


def forward(
    cfg: ModelConfig,
    params: dict,
    rope: dict,
    tokens: jnp.ndarray,  # [T] int32
    cache: dict,  # {"k","v": [L, S, n_kv, hd]}
    pos,  # scalar int32: sequence position of tokens[0]
) -> tuple:
    """Process T tokens starting at ``pos``. Returns (logits [T, vocab] f32, new cache).

    T==1 is the decode step; larger T is batched prefill (the reference feeds
    prompt tokens one at a time — batching them is the first TPU win).
    """
    x = params["embedding"][tokens].astype(cfg.jax_dtype)
    if cfg.embedding_scale != 1.0:
        x = x * jnp.asarray(cfg.embedding_scale, cfg.jax_dtype)

    def layer_step(x, layer):
        lp, k_cache, v_cache = layer
        att_out, k_cache, v_cache = _attn_block(cfg, lp, rope, x, k_cache, v_cache, pos)
        x = x + att_out
        xb = rmsnorm(x, lp["rms_ffn"], cfg.norm_eps)
        x = x + _dense_ffn(cfg, lp, xb)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"])
    )

    x = rmsnorm(x, params["rms_final"], cfg.norm_eps)
    logits = (x @ params["wcls"]).astype(jnp.float32)
    if cfg.logit_scale != 1.0:
        logits = logits * cfg.logit_scale
    return logits, {"k": new_k, "v": new_v}
