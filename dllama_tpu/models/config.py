"""Runtime model configuration, derived from the on-disk ModelSpec."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from dllama_tpu.formats.spec import ArchType, HiddenAct, ModelSpec
from dllama_tpu.ops import rope as rope_ops

# Grok-1 scalings (`/root/reference/src/grok1-tasks.cpp:11-14,269-272`)
GROK_EMBEDDING_SCALE = 78.38367176906169
GROK_LOGIT_SCALE = 0.5773502691896257

#: user-facing dtype aliases (CLI / exporter flags -> numpy dtype names)
DTYPE_ALIASES = {"f8": "float8_e4m3fn"}


def resolve_dtype(name: str | None, default: str) -> jnp.dtype:
    """Flag string (or None) -> jnp.dtype, honoring DTYPE_ALIASES."""
    return jnp.dtype(DTYPE_ALIASES.get(name, name) or default)


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    arch: str  # "llama" | "grok1" | "mixtral"
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    head_size: int
    kv_dim: int
    n_experts: int = 0
    n_active_experts: int = 0
    hidden_act: str = "silu"
    rope_theta: float = 10000.0
    # None = "derive from arch" for the four arch-implied fields below; an
    # explicitly passed value always wins (ablation configs stay expressible)
    rope_style: str | None = None
    embedding_scale: float | None = None
    logit_scale: float | None = None
    # grok1 re-normalizes after attention / moe output
    # (`/root/reference/src/grok1-tasks.cpp:16-41,244-262`)
    post_norms: bool | None = None
    norm_eps: float = 1e-5
    dtype: str = "float32"

    def __post_init__(self):
        # Arch-implied semantics, resolved from None sentinels: the Grok
        # scalings, post-norms and the half-split rotary ARE the arch
        # (`/root/reference/src/grok1-tasks.cpp`; from_spec hard-derives all
        # of them from arch alone), so an unset field follows the arch —
        # while an EXPLICIT value (even one equal to the generic default,
        # e.g. grok1 with logit_scale=1.0 in an ablation) is preserved
        # as passed. hidden_act is NOT derived: it is an independent
        # file-header field (formats.spec.HiddenAct) that a grok1
        # checkpoint can legitimately set to silu.
        grok = self.arch == "grok1"
        if self.rope_style is None:
            object.__setattr__(
                self, "rope_style",
                rope_ops.HALF if self.arch in ("grok1", "mixtral")
                else rope_ops.INTERLEAVED)
        if self.embedding_scale is None:
            object.__setattr__(
                self, "embedding_scale", GROK_EMBEDDING_SCALE if grok else 1.0)
        if self.logit_scale is None:
            object.__setattr__(
                self, "logit_scale", GROK_LOGIT_SCALE if grok else 1.0)
        if self.post_norms is None:
            object.__setattr__(self, "post_norms", grok)

    @property
    def jax_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @classmethod
    def from_spec(cls, spec: ModelSpec, dtype: str = "float32") -> "ModelConfig":
        arch = {ArchType.LLAMA: "llama", ArchType.GROK1: "grok1", ArchType.MIXTRAL: "mixtral"}[
            spec.arch
        ]
        # Grok/Mixtral use the half-split (NeoX) rotary layout, Llama the
        # interleaved one (`/root/reference/src/transformer.cpp:398-402`).
        rope_style = rope_ops.HALF if arch in ("grok1", "mixtral") else rope_ops.INTERLEAVED
        return cls(
            arch=arch,
            dim=spec.dim,
            hidden_dim=spec.hidden_dim,
            n_layers=spec.n_layers,
            n_heads=spec.n_heads,
            n_kv_heads=spec.n_kv_heads,
            vocab_size=spec.vocab_size,
            seq_len=spec.seq_len,
            head_size=spec.head_size,
            kv_dim=spec.kv_dim,
            n_experts=spec.n_experts,
            n_active_experts=spec.n_active_experts,
            hidden_act="gelu" if spec.hidden_act == HiddenAct.GELU else "silu",
            rope_theta=spec.rope_theta,
            rope_style=rope_style,
            embedding_scale=GROK_EMBEDDING_SCALE if arch == "grok1" else 1.0,
            logit_scale=GROK_LOGIT_SCALE if arch == "grok1" else 1.0,
            post_norms=arch == "grok1",
            dtype=dtype,
        )
