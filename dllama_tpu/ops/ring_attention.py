"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

The reference never distributes the sequence dimension: its attention iterates
the full local KV history serially per token and seqLen is capped by a 16-bit
position type (`/root/reference/src/llama2-tasks.cpp:62-93`,
`/root/reference/src/transformer.hpp:9`). On TPU, long context is a
first-class axis: each device holds a contiguous sequence chunk of Q/K/V, and
K/V chunks rotate around the ring over ICI (``jax.lax.ppermute``) while every
device accumulates its queries' attention with an online (streaming) softmax —
compute and memory per device stay O(seq/n_sp), and the rotation overlaps
with the per-step attention matmuls.

This is the Ring Attention construction (Liu et al. 2023; see PAPERS.md) — the
blockwise-parallel formulation with a running (max, denominator, accumulator)
triple, causal masking resolved per (query-chunk, kv-chunk) pair:

* kv chunk strictly before the query chunk -> attend to all of it
* same chunk -> local causal mask
* kv chunk after the query chunk -> fully masked, contributes nothing

Differentiable end-to-end (ppermute has a transpose rule), so the training
step shards sequence the same way.

Usage: wrap with ``shard_map`` over a mesh with an ``sp`` axis — see
``ring_self_attention`` for the canonical causal self-attention entry and
``tests/test_ring_attention.py`` for the invariance proof vs dense attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dllama_tpu import compat
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _chunk_scores(q, k):
    """Raw scaled scores for one (q-chunk, kv-chunk) pair.

    q [B, Tq, Hkv, G, D]; k [B, Tkv, Hkv, D] -> [B, Hkv, G, Tq, Tkv].
    """
    return jnp.einsum("btkgh,bskh->bkgts", q, k) / jnp.sqrt(
        jnp.float32(q.shape[-1])
    )


def ring_attention_kernel(
    q: jnp.ndarray,  # [B, Tc, Hkv, G, D] f32 — local query chunk
    k: jnp.ndarray,  # [B, Tc, Hkv, D] f32 — local key chunk
    v: jnp.ndarray,  # [B, Tc, Hkv, D] f32 — local value chunk
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Per-device body (call under shard_map). Returns [B, Tc, Hkv, G, D].

    Chunks are laid out in ring order: device i holds sequence positions
    ``[i*Tc, (i+1)*Tc)``.
    """
    n = compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tc, Hkv, G, D = q.shape

    local_mask = (
        jnp.tril(jnp.ones((Tc, Tc), bool)) if causal else None
    )

    acc = jnp.zeros((B, Hkv, G, Tc, D), jnp.float32)
    row_max = jnp.full((B, Hkv, G, Tc), NEG_INF, jnp.float32)
    denom = jnp.zeros((B, Hkv, G, Tc), jnp.float32)

    # rotate kv around the ring: after s steps we hold the chunk of device
    # (idx - s) mod n
    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate(s, k_cur, v_cur, acc, row_max, denom):
        src = (idx - s) % n  # whose chunk we hold this step

        scores = _chunk_scores(q, k_cur)  # [B,Hkv,G,Tq,Tkv]
        if causal:
            # src > idx: kv chunk is entirely in the future -> mask all.
            # src == idx: local causal. src < idx: no mask.
            scores = jnp.where(
                src == idx,
                jnp.where(local_mask[None, None, None], scores, NEG_INF),
                jnp.where(src > idx, jnp.full_like(scores, NEG_INF), scores),
            )

        chunk_max = scores.max(axis=-1)  # [B,Hkv,G,Tq]
        new_max = jnp.maximum(row_max, chunk_max)
        # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
        safe_max = jnp.where(new_max <= NEG_INF, 0.0, new_max)
        correction = jnp.exp(jnp.maximum(row_max - safe_max, NEG_INF))
        correction = jnp.where(row_max <= NEG_INF, 0.0, correction)
        p = jnp.exp(scores - safe_max[..., None])
        p = jnp.where(scores <= NEG_INF, 0.0, p)

        acc = acc * correction[..., None] + jnp.einsum("bkgts,bskh->bkgth", p, v_cur)
        denom = denom * correction + p.sum(axis=-1)
        return acc, new_max, denom

    def step(carry, s):
        k_cur, v_cur, acc, row_max, denom = carry
        acc, row_max, denom = accumulate(s, k_cur, v_cur, acc, row_max, denom)
        # scan over static length: reverse-differentiable (the training path
        # shards sequence too), unlike fori_loop/while_loop
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, acc, row_max, denom), None

    k_f, v_f = k.astype(jnp.float32), v.astype(jnp.float32)
    if n > 1:
        # the last chunk is accumulated OUTSIDE the scan: n-1 rotations move
        # the data n-1 hops, and no dead final ppermute rides the critical path
        (k_f, v_f, acc, row_max, denom), _ = jax.lax.scan(
            step, (k_f, v_f, acc, row_max, denom), jnp.arange(n - 1)
        )
    acc, row_max, denom = accumulate(n - 1, k_f, v_f, acc, row_max, denom)
    out = acc / jnp.where(denom == 0.0, 1.0, denom)[..., None]
    return jnp.transpose(out, (0, 3, 1, 2, 4))  # [B, Tc, Hkv, G, D]


def ring_self_attention(
    q: jnp.ndarray,  # [B, T, Hq, D] — sequence-sharded over axis_name
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,  # [B, T, Hkv, D]
    mesh,
    axis_name: str = "sp",
    causal: bool = True,
) -> jnp.ndarray:
    """Causal GQA self-attention with the sequence dim sharded over
    ``axis_name``. Drop-in for a dense softmax(QK^T)V — returns [B, T, Hq, D]
    with the same sharding as q.

    All other mesh axes stay automatic (XLA keeps whatever batch/head
    shardings the surrounding program chose).
    """
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv

    spec = P(None, axis_name, None, None)

    def body(qc, kc, vc):
        out = ring_attention_kernel(
            qc.astype(jnp.float32).reshape(*qc.shape[:2], Hkv, G, D),
            kc.astype(jnp.float32), vc.astype(jnp.float32),
            axis_name, causal=causal,
        )
        return out.reshape(*qc.shape[:2], Hq, D).astype(q.dtype)

    mapped = compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
        axis_names={axis_name},
    )
    return mapped(q, k, v)
