"""Flash-decode attention: read ONLY the live KV-cache prefix.

The dense decode path (ops.attention.gqa_attention) is a static-shape masked
einsum — idiomatic XLA, but it streams the ENTIRE [S, kv, hd] cache from HBM
every token and, under the layer scan, first materializes each layer's slab
out of the stacked [L, S, kv, hd] cache (a dynamic-slice copy, the same
failure mode the stacked qmatmul kernels eliminated for weights). At short
context that is a few percent of decode bytes; at S=4096 the cache is
2.1 GB/token on a 7B — comparable to the weights themselves — and almost all
of it masked out.

This kernel is the TPU-native fix (the online-softmax flash-decoding
pattern): the caches stay in HBM (``memory_space=ANY``); a scalar-prefetched
``[layer, n_live_blocks]`` pair steers a ``fori_loop`` whose trip count is
the number of CACHE BLOCKS THAT ACTUALLY CONTAIN HISTORY, each iteration
DMA-ing one [BS, hd] K and V block per kv-head into VMEM scratch and folding
it into running (m, l, acc) online-softmax state. Bytes/token scale with
``pos``, not ``seq_len``, and the stacked cache is read in place.

Decode-only by design (T <= a few spec-verify rows): prefill stays on the
dense path, where the causal mask is half-live anyway and the MXU is the
bottleneck, not bandwidth.

Semantics match gqa_attention exactly (same masking: query row t attends to
cache positions <= pos + t; softmax in f32). Verified against it by
tests/test_flash_decode.py in interpret mode; opt in on hardware with
DLLAMA_FLASH_DECODE=1 until it is benchmark-proven (scripts/measure_r04b.sh
ablation), then the default can flip.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

#: cache-block length (sequence positions per DMA). 256 divides every model
#: seq_len the bench/CLI loads (512/1024/2048/4096/...); callers must fall
#: back to the dense path when S % block is nonzero.
BLOCK_S = 256


def flash_enabled() -> bool:
    return os.environ.get("DLLAMA_FLASH_DECODE", "0") == "1"


def supports(T: int, S: int, cache_dtype) -> bool:
    """Shapes/dtypes this kernel handles; anything else → dense path.

    T covers plain decode (1) through spec-verify batches (draft_len+1 = 9
    at the default draft_len=8) with margin; row padding rounds T*group up
    to a sublane multiple either way. f8 caches stay dense until the
    Mosaic f8 conversion path is hardware-validated."""
    return (
        T <= 16
        and S % BLOCK_S == 0
        and jnp.dtype(cache_dtype) in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32))
    )


def engages(weights_quantized: bool, T: int, S: int, cache_dtype) -> bool:
    """THE single gate for whether decode attention runs this kernel —
    used by both the model layer and the bench's result tagging, so the
    two can never drift. The quantized condition exists because only the
    quantized engine takes the layer-scan (scalar-prefetch) path the
    flash wiring lives on."""
    return weights_quantized and flash_enabled() and supports(T, S, cache_dtype)


def _kernel(idx_ref, q_ref, qpos_ref, k_hbm, v_hbm, o_ref,
            k_buf, v_buf, k_sem, v_sem, *, block_s):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    h = pl.program_id(0)
    layer = idx_ref[0]
    n_blk = idx_ref[1]
    q = q_ref[0].astype(jnp.float32)  # [Tg, hd]
    Tg, hd = q.shape
    qpos = qpos_ref[...]  # [Tg, 1] int32
    scale = jax.lax.rsqrt(jnp.float32(hd))

    # double-buffered: DMA for block i+1 is in flight while block i computes
    # (k_buf/v_buf are [2, BS, hd]; per-slot semaphores)
    def k_dma(i, slot):
        return pltpu.make_async_copy(
            k_hbm.at[layer, pl.ds(i * block_s, block_s), h],
            k_buf.at[slot], k_sem.at[slot])

    def v_dma(i, slot):
        return pltpu.make_async_copy(
            v_hbm.at[layer, pl.ds(i * block_s, block_s), h],
            v_buf.at[slot], v_sem.at[slot])

    k_dma(0, 0).start()
    v_dma(0, 0).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        nxt = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_blk)
        def _prefetch():
            k_dma(i + 1, nxt).start()
            v_dma(i + 1, nxt).start()

        k_dma(i, slot).wait()
        k = k_buf[slot].astype(jnp.float32)  # [BS, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Tg, BS]
        key_idx = i * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (Tg, block_s), 1)
        s = jnp.where(key_idx <= qpos, s, jnp.float32(-1e30))
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        v_dma(i, slot).wait()
        v = v_buf[slot].astype(jnp.float32)  # [BS, hd]
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    init = (
        jnp.full((Tg, 1), -1e30, jnp.float32),
        jnp.zeros((Tg, 1), jnp.float32),
        jnp.zeros((Tg, hd), jnp.float32),
    )
    m, l, acc = jax.lax.fori_loop(0, n_blk, body, init)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_decode_attention(
    q: jnp.ndarray,        # [T, n_heads, head_size]
    k_cache: jnp.ndarray,  # [L, S, n_kv_heads, head_size] (L=1 for unstacked)
    v_cache: jnp.ndarray,  # same
    pos: jnp.ndarray,      # scalar int32: sequence position of q[0]
    layer: jnp.ndarray,    # scalar int32 selecting the cache layer
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Online-softmax decode attention over the live cache prefix only.

    Returns [T, n_heads, head_size], numerically matching
    ``gqa_attention(q, k_cache[layer], v_cache[layer], pos)``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    T, n_heads, hd = q.shape
    L, S, n_kv, _ = k_cache.shape
    group = n_heads // n_kv
    assert S % BLOCK_S == 0, (S, BLOCK_S)

    # rows = (t, g) pairs per kv head: row // group = query offset t
    Tg = T * group
    # round UP to a sublane multiple (not just floor at 8): T=5 x group=2
    # would otherwise hand Mosaic a 10-sublane block; pad rows are
    # discarded after
    Tgp = max(8, -(-Tg // 8) * 8)
    qr = q.reshape(T, n_kv, group, hd).transpose(1, 0, 2, 3).reshape(n_kv, Tg, hd)
    if Tgp != Tg:
        qr = jnp.pad(qr, ((0, 0), (0, Tgp - Tg), (0, 0)))
    row_t = (jnp.arange(Tgp, dtype=jnp.int32) // group).clip(0, T - 1)
    qpos = (pos + row_t)[:, None]  # [Tgp, 1]; pad rows clamp to a live pos

    pos = jnp.asarray(pos, jnp.int32)
    n_blk = (pos + T + BLOCK_S - 1) // BLOCK_S  # live cache blocks
    idx = jnp.stack([jnp.asarray(layer, jnp.int32).reshape(()), n_blk])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_kv,),
        in_specs=[
            pl.BlockSpec((1, Tgp, hd), lambda h, idx: (h, 0, 0)),
            pl.BlockSpec((Tgp, 1), lambda h, idx: (0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Tgp, hd), lambda h, idx: (h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, BLOCK_S, hd), k_cache.dtype),
            pltpu.VMEM((2, BLOCK_S, hd), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, block_s=BLOCK_S),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_kv, Tgp, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(idx, qr, qpos, k_cache, v_cache)
    return (
        out[:, :Tg]
        .reshape(n_kv, T, group, hd)
        .transpose(1, 0, 2, 3)
        .reshape(T, n_heads, hd)
    )
