"""Flash-decode attention: read ONLY the live KV-cache prefix.

The dense decode path (ops.attention.gqa_attention) is a static-shape masked
einsum — idiomatic XLA, but it streams the ENTIRE [S, kv, hd] cache from HBM
every token and, under the layer scan, first materializes each layer's slab
out of the stacked [L, S, kv, hd] cache (a dynamic-slice copy, the same
failure mode the stacked qmatmul kernels eliminated for weights). At short
context that is a few percent of decode bytes; at S=4096 the cache is
2.1 GB/token on a 7B — comparable to the weights themselves — and almost all
of it masked out.

This kernel is the TPU-native fix (the online-softmax flash-decoding
pattern): the caches stay in HBM (``memory_space=ANY``); a scalar-prefetched
``[layer, n_live_blocks]`` pair steers a ``fori_loop`` whose trip count is
the number of CACHE BLOCKS THAT ACTUALLY CONTAIN HISTORY, each iteration
DMA-ing one [BS, hd] K and V block per kv-head into VMEM scratch and folding
it into running (m, l, acc) online-softmax state. Bytes/token scale with
``pos``, not ``seq_len``, and the stacked cache is read in place.

Decode-only by design (T <= a few spec-verify rows): prefill stays on the
dense path, where the causal mask is half-live anyway and the MXU is the
bottleneck, not bandwidth.

Semantics match gqa_attention exactly (same masking: query row t attends to
cache positions <= pos + t; softmax in f32). Verified against it by
tests/test_flash_decode.py in interpret mode; opt in on hardware with
DLLAMA_FLASH_DECODE=1 until it is benchmark-proven (scripts/measure_r04b.sh
ablation), then the default can flip.
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp

from dllama_tpu import compat

#: cache-block length (sequence positions per DMA). 256 divides every model
#: seq_len the bench/CLI loads (512/1024/2048/4096/...); callers must fall
#: back to the dense path when S % block is nonzero.
BLOCK_S = 256


def flash_enabled() -> bool:
    return os.environ.get("DLLAMA_FLASH_DECODE", "0") == "1"


def supports(T: int, S: int, cache_dtype) -> bool:
    """Shapes/dtypes this kernel handles; anything else → dense path.

    T covers plain decode (1) through spec-verify batches (draft_len+1 = 9
    at the default draft_len=8) with margin; row padding rounds T*group up
    to a sublane multiple either way. f8 (float8_e4m3fn) caches are read
    through the same VMEM scratch path with the f32 upcast in compute —
    the combination long context wants (half the cache bytes AND
    live-prefix-only reads)."""
    return (
        T <= 16
        and S % BLOCK_S == 0
        and jnp.dtype(cache_dtype) in (jnp.dtype(jnp.bfloat16),
                                       jnp.dtype(jnp.float32),
                                       jnp.dtype(jnp.float8_e4m3fn))
    )


#: (T, S, dtype) combinations already warned about — the fallback must be
#: observable (ADVICE r04) but not per-trace noisy.
_declined: set = set()


def engages(T: int, S: int, cache_dtype) -> bool:
    """THE single gate for whether decode attention runs this kernel —
    used by the model layers (quantized layer-scan AND dense index-scan
    paths) and the bench's result tagging, so label and measured path can
    never drift. When the user asked for flash but the shapes decline it,
    say so once on stderr: a silent dense fallback under
    DLLAMA_FLASH_DECODE=1 reads as "flash is on" otherwise."""
    if not flash_enabled():
        return False
    if supports(T, S, cache_dtype):
        return True
    if T > 16:
        # prefill-sized T declining is the DESIGN (the causal mask is
        # half-live and the MXU is the bottleneck there, not bandwidth) —
        # warning would misread as "flash is off" on runs whose T=1 decode
        # engages it normally
        return False
    key = (T, S, jnp.dtype(cache_dtype).name)
    if key not in _declined:
        _declined.add(key)
        print(f"dllama: DLLAMA_FLASH_DECODE=1 but flash decode declines "
              f"T={T} S={S} cache={key[2]} (need S%{BLOCK_S}==0 and a "
              f"bf16/f32/f8 cache) — dense attention path used",
              file=sys.stderr, flush=True)
    return False


def _kernel(idx_ref, q_ref, qpos_ref, k_hbm, v_hbm, o_ref,
            k_buf, v_buf, k_sem, v_sem, *, block_s):
    """Unified (batch, kv-head) grid program. idx_ref = [layer, n_blk[0],
    ..., n_blk[B-1]]; caches are [L, B, S, kv, hd]; each program reads only
    row b's live blocks for head h."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    h = pl.program_id(1)
    layer = idx_ref[0]
    n_blk = idx_ref[1 + b]
    q = q_ref[0, 0].astype(jnp.float32)  # [Tg, hd]
    Tg, hd = q.shape
    qpos = qpos_ref[0]  # [Tg, 1] int32
    scale = jax.lax.rsqrt(jnp.float32(hd))

    # double-buffered: DMA for block i+1 is in flight while block i computes
    # (k_buf/v_buf are [2, BS, hd]; per-slot semaphores)
    def k_dma(i, slot):
        return pltpu.make_async_copy(
            k_hbm.at[layer, b, pl.ds(i * block_s, block_s), h],
            k_buf.at[slot], k_sem.at[slot])

    def v_dma(i, slot):
        return pltpu.make_async_copy(
            v_hbm.at[layer, b, pl.ds(i * block_s, block_s), h],
            v_buf.at[slot], v_sem.at[slot])

    k_dma(0, 0).start()
    v_dma(0, 0).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        nxt = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_blk)
        def _prefetch():
            k_dma(i + 1, nxt).start()
            v_dma(i + 1, nxt).start()

        k_dma(i, slot).wait()
        k = k_buf[slot].astype(jnp.float32)  # [BS, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Tg, BS]
        key_idx = i * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (Tg, block_s), 1)
        s = jnp.where(key_idx <= qpos, s, jnp.float32(-1e30))
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        v_dma(i, slot).wait()
        v = v_buf[slot].astype(jnp.float32)  # [BS, hd]
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    init = (
        jnp.full((Tg, 1), -1e30, jnp.float32),
        jnp.zeros((Tg, 1), jnp.float32),
        jnp.zeros((Tg, hd), jnp.float32),
    )
    m, l, acc = jax.lax.fori_loop(0, n_blk, body, init)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _launch(qr, qpos, k5, v5, n_blk, layer, interpret):
    """qr [B, n_kv, Tgp, hd], qpos [B, Tgp, 1] i32, caches [L, B, S, kv,
    hd], n_blk [B] i32 live-block counts -> [B, n_kv, Tgp, hd]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, n_kv, Tgp, hd = qr.shape
    idx = jnp.concatenate(
        [jnp.asarray(layer, jnp.int32).reshape(1), n_blk.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, Tgp, hd), lambda b, h, idx: (b, h, 0, 0)),
            pl.BlockSpec((1, Tgp, 1), lambda b, h, idx: (b, 0, 0)),  # dllama: allow[PALLAS-001] reason=whole-array lane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, Tgp, hd), lambda b, h, idx: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, BLOCK_S, hd), k5.dtype),
            pltpu.VMEM((2, BLOCK_S, hd), v5.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_s=BLOCK_S),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_kv, Tgp, hd), qr.dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(idx, qr, qpos, k5, v5)


def _rows(q, n_kv, group, Tg, Tgp):
    """[.., T, n_heads, hd] -> row layout [.., n_kv, Tgp, hd] (row = t*group+g)."""
    lead = q.shape[:-3]
    T, _, hd = q.shape[-3:]
    qr = (q.reshape(*lead, T, n_kv, group, hd)
          .swapaxes(-4, -3)
          .reshape(*lead, n_kv, Tg, hd))
    if Tgp != Tg:
        pad = [(0, 0)] * (qr.ndim - 2) + [(0, Tgp - Tg), (0, 0)]
        qr = jnp.pad(qr, pad)
    return qr


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_decode_attention(
    q: jnp.ndarray,        # [T, n_heads, head_size]
    k_cache: jnp.ndarray,  # [L, S, n_kv_heads, head_size] (L=1 for unstacked)
    v_cache: jnp.ndarray,  # same
    pos: jnp.ndarray,      # scalar int32: sequence position of q[0]
    layer: jnp.ndarray,    # scalar int32 selecting the cache layer
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Online-softmax decode attention over the live cache prefix only.

    Returns [T, n_heads, head_size], numerically matching
    ``gqa_attention(q, k_cache[layer], v_cache[layer], pos)``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    T, n_heads, hd = q.shape
    L, S, n_kv, _ = k_cache.shape
    group = n_heads // n_kv
    assert S % BLOCK_S == 0, (S, BLOCK_S)

    # rows = (t, g) pairs per kv head: row // group = query offset t,
    # rounded UP to a sublane multiple (pad rows are discarded after)
    Tg = T * group
    Tgp = max(8, -(-Tg // 8) * 8)
    qr = _rows(q, n_kv, group, Tg, Tgp)[None]  # B=1
    row_t = (jnp.arange(Tgp, dtype=jnp.int32) // group).clip(0, T - 1)
    pos = jnp.asarray(pos, jnp.int32)
    qpos = (pos + row_t)[None, :, None]  # [1, Tgp, 1]; pads clamp live
    n_blk = ((pos + T + BLOCK_S - 1) // BLOCK_S).reshape(1)

    out = _launch(qr, qpos, k_cache[:, None], v_cache[:, None], n_blk,
                  layer, interpret)
    return (
        out[0, :, :Tg]
        .reshape(n_kv, T, group, hd)
        .transpose(1, 0, 2, 3)
        .reshape(T, n_heads, hd)
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_decode_attention_batched(
    q: jnp.ndarray,        # [B, n_heads, head_size] — one token per sequence
    k_cache: jnp.ndarray,  # [L, B, S, n_kv_heads, head_size]
    v_cache: jnp.ndarray,  # same
    pos: jnp.ndarray,      # [B] int32: each row's position
    layer: jnp.ndarray,    # scalar int32 selecting the cache layer
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Batched decode: B independent sequences, each reading only ITS OWN
    live prefix (row b stops at pos[b], not max(pos)). Matches
    vmap(gqa_attention) over the per-row slabs."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, n_heads, hd = q.shape
    L, Bc, S, n_kv, _ = k_cache.shape
    assert B == Bc and S % BLOCK_S == 0, (B, Bc, S, BLOCK_S)
    group = n_heads // n_kv
    Tg = group
    Tgp = max(8, -(-Tg // 8) * 8)
    qr = _rows(q[:, None], n_kv, group, Tg, Tgp)  # [B, n_kv, Tgp, hd]
    pos = jnp.asarray(pos, jnp.int32)
    qpos = jnp.broadcast_to(pos[:, None, None], (B, Tgp, 1))
    n_blk = (pos + 1 + BLOCK_S - 1) // BLOCK_S  # [B]

    out = _launch(qr, qpos, k_cache, v_cache, n_blk, layer, interpret)
    return out[:, :, :Tg].reshape(B, n_kv * group, hd)


def probe_kernel(cache: str = "bf16", timeout_s: int = 240) -> tuple:
    """Compile+run one tiny flash-decode kernel in a SUBPROCESS with the
    given cache dtype ("bf16" | "f8") -> (ok, failure_detail).

    For callers that haven't touched the backend yet (bench, CLI serve): a
    Mosaic rejection — plausible for the f8 upcast path until it is
    hardware-validated — surfaces here as a clean (False, detail) the
    caller can downgrade on (unset DLLAMA_FLASH_DECODE, run dense
    attention) instead of crashing on the first decode dispatch. The
    subprocess matters twice over: a down TPU tunnel hangs backend init in
    native code (un-timeout-able in-process), and some TPU runtimes are
    per-process exclusive, so a probe spawned AFTER the parent holds the
    chip would silently land on CPU and validate nothing.

    Skips (returns True) when the default backend is not TPU — interpret
    mode has nothing Mosaic-level to validate — and when DLLAMA_PLATFORM
    forces the parent off-TPU.
    """
    import subprocess

    forced = os.environ.get("DLLAMA_PLATFORM")
    if forced and forced != "tpu":
        return True, "platform forced off-TPU; interpret mode, nothing to probe"
    cache_expr = "jnp.float8_e4m3fn" if cache == "f8" else "jnp.bfloat16"
    # the child must resolve THIS package even when the caller runs from an
    # arbitrary cwd (the CLI does; bench chdirs to the repo root itself)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    code = (
        f"import sys; sys.path.insert(0, {pkg_root!r})\n"
        "import jax\n"
        + (f"jax.config.update('jax_platforms', {forced!r})\n" if forced else "")
        + "import jax.numpy as jnp\n"
        "if jax.default_backend() != 'tpu':\n"
        "    print('FLASH_OK (non-tpu backend: interpret mode)')\n"
        "    raise SystemExit(0)\n"
        "print('BACKEND_TPU_OK')\n"
        "from dllama_tpu.ops import flash_decode\n"
        "q = jnp.ones((1, 8, 128), jnp.bfloat16)\n"
        f"k = jnp.ones((1, 512, 4, 128), {cache_expr})\n"
        f"v = jnp.ones((1, 512, 4, 128), {cache_expr})\n"
        "y = flash_decode.flash_decode_attention(\n"
        "    q, k, v, jnp.int32(300), jnp.int32(0))\n"
        "jax.block_until_ready(y)\n"
        "print('FLASH_OK')\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s}s (TPU tunnel down?)"
    if proc.returncode == 0 and "FLASH_OK" in proc.stdout:
        return True, ""
    detail = ((proc.stdout or "") + (proc.stderr or "")).strip()
    if len(detail) > 500:
        detail = detail[:100] + " ... " + detail[-400:]
    return False, detail
