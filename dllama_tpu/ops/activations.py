"""Activations matching the reference kernels.

* ``silu``: ``x * sigmoid(x)`` (`/root/reference/src/funcs.cpp:499-506`).
* ``gelu``: tanh approximation ``0.5*x*(1+tanh(sqrt(2/pi)*(x+0.044715*x^3)))``
  (`/root/reference/src/funcs.cpp:490-497`) — used by Grok-1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

GELU_CONST = 0.044715
SQRT_2_OVER_PI = 0.7978845608028654


def silu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(x)


def gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    out = 0.5 * xf * (1.0 + jnp.tanh(SQRT_2_OVER_PI * (xf + GELU_CONST * xf * xf * xf)))
    return out.astype(x.dtype)


ACTIVATIONS = {"silu": silu, "gelu": gelu_tanh}
