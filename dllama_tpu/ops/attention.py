"""Grouped-query attention over a fixed-size KV cache.

Semantics mirror the reference per-head loop
(`/root/reference/src/llama2-tasks.cpp:54-94`): score = q.k / sqrt(head_size),
softmax over positions 0..pos (inclusive), weighted sum of V. The reference
iterates positions serially per token; here the whole history is one masked
MXU-friendly einsum, and prefill processes T query positions at once under a
causal mask — numerically identical, shapes static for XLA.

Softmax runs in f32 whatever the activation dtype (the reference is all-f32).
"""

from __future__ import annotations

import jax.numpy as jnp


def gqa_attention(
    q: jnp.ndarray,  # [T, n_heads, head_size]
    k_cache: jnp.ndarray,  # [S, n_kv_heads, head_size]
    v_cache: jnp.ndarray,  # [S, n_kv_heads, head_size]
    pos: jnp.ndarray,  # scalar int32: position of q[0] in the sequence
) -> jnp.ndarray:
    """Masked GQA attention. Returns [T, n_heads, head_size].

    The cache must already contain this step's K/V at positions pos..pos+T-1.
    Query t attends to cache positions <= pos + t; everything later is masked.
    """
    T, n_heads, head_size = q.shape
    S, n_kv_heads, _ = k_cache.shape
    group = n_heads // n_kv_heads

    qf = q.astype(jnp.float32).reshape(T, n_kv_heads, group, head_size)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)

    scores = jnp.einsum("tkgh,skh->tkgs", qf, kf) / jnp.sqrt(jnp.float32(head_size))

    key_idx = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    query_pos = pos + jnp.arange(T, dtype=jnp.int32)[:, None]  # [T, 1]
    mask = key_idx <= query_pos  # [T, S]
    scores = jnp.where(mask[:, None, None, :], scores, jnp.float32(-1e30))

    att = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    att = att / att.sum(axis=-1, keepdims=True)

    out = jnp.einsum("tkgs,skh->tkgh", att, vf)
    return out.reshape(T, n_heads, head_size).astype(q.dtype)
