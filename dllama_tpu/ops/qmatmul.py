"""Fused dequantize-matmul Pallas TPU kernels for block-quantized weights.

The reference's production decode path is ``matmulQ40vQ80`` — activations
quantized to Q80 on the fly, weights stored as Q40 nibbles, SIMD dot in int
space (`/root/reference/src/funcs.cpp:267-385`). On TPU the equivalent win is
**bandwidth**, not ALU width: single-token decode is HBM-bound, so keeping
weights as 4-bit blocks in HBM and dequantizing *inside* the matmul kernel
(VMEM tiles, never materializing the bf16 matrix in HBM) cuts the bytes/token
by ~4x versus bf16 weights.

Layouts (chosen for Mosaic-friendly unpacking — all kernel ops are int32/f32
vector ops; int8/uint8 arithmetic does not legalize on TPU):

* **Q80**: ``int8 [in, out]`` quants + ``f32 [in/32, out]`` per-block scales.
  Block b covers input rows ``32b..32b+31`` (the reference's 32-value blocks,
  `/root/reference/src/quants.hpp:21-24`, transposed to kernel layout).
* **Q40**: ``uint8 [in/2, out]`` packed nibbles + two ``f32 [in/64, out]``
  scale planes. Byte ``32s + j`` holds input row ``64s + j`` in its low nibble
  (scale plane ``s_lo[s]``) and row ``64s + 32 + j`` in its high nibble
  (``s_hi[s]``) — i.e. consecutive 32-blocks pair into one byte column, so
  the kernel splits the activation by 32-row half-superblocks *outside* the
  kernel (pure reshape) instead of interleaving lanes inside it.

Nibbles store ``q + 8`` with dequant ``(q - 8) * delta``, matching
`/root/reference/src/quants.cpp:166-180` bit-for-bit, so repacking a published
Q40 checkpoint is lossless (see ``repack_q40`` / ``formats.weights``).

Kernels run on TPU via Mosaic and anywhere else via ``interpret=True``
(automatic on non-TPU backends), which is how the CPU test suite covers them.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu import compat

from dllama_tpu.quants import blocks

QK = blocks.QK  # 32 values per quantization block

#: q40 "no-subtract" dequant: the kernel drops the ``- 8`` nibble recentering
#: (the VPU op the dequant is bound on) and the caller subtracts the exact
#: correction ``8 * sum_blocks blocksum(x) * delta`` via two small MXU dots
#: against the scale planes. Measured on v5e (scripts/qkernel_experiments.py,
#: K=4096 O=11008): 537 GB/s effective vs 380 GB/s for the subtracting
#: kernel, at ~2x the (still block-quantization-sized) rounding error —
#: 7.6e-3 vs 3.7e-3 max-rel, both well inside the 2e-2 the q40 format itself
#: implies. Opt out with DLLAMA_Q40_NOSUB=0 for the bit-conservative kernel.
Q40_NOSUB = os.environ.get("DLLAMA_Q40_NOSUB", "1") != "0"


def norm_fusion_enabled() -> bool:
    """DLLAMA_FUSE_NORM=1: fuse the rmsnorm epilogue into the projection
    kernels' t-blocks (``qmatmul_norm``) instead of materializing the
    normalized activation in HBM between two dispatches. Read per call (not
    import time) so tests and the bench can flip it."""
    return os.environ.get("DLLAMA_FUSE_NORM", "0") == "1"


def norm_fusion_engages(w) -> bool:
    """THE gate for the norm+projection fusion at one call site: the flag is
    on AND the matrix is quantized (dense matmuls already fuse their norm
    under XLA; the Pallas custom call is what breaks that fusion)."""
    return norm_fusion_enabled() and isinstance(w, QuantTensor)


def rmsnorm_inv(x: jnp.ndarray, eps: float) -> jnp.ndarray:
    """The per-row normalizer ``1/sqrt(mean(x^2) + eps)`` as [T, 1] f32 —
    computed OUTSIDE the fused kernels (it needs the whole logical K row;
    the kernels see K in bk-blocks) with exactly ops.norms.rmsnorm's op
    order so the in-kernel epilogue is bit-identical to the composition."""
    xf = x.astype(jnp.float32)
    return jnp.reciprocal(
        jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps))


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


#: cap on bk*bo cells per tile. The binding constraint is not the ~0.625 B/cell
#: the packed tile + scales occupy in HBM but the kernel's scoped VMEM: the
#: uint8 tile widens to int32 and dequantizes through f32 intermediates, which
#: Mosaic stack-allocates at ~3 B/cell (measured: a 5.77M-cell tile asked for
#: 17.9 MB of scoped VMEM against the 16 MB limit). 2M cells ≈ 6.5 MB scoped,
#: leaving room for the rest of the decode program's kernels.
_TILE_CELL_CAP = 2 * 2**20


#: input-dim padding unit per kind. Mosaic requires the second-to-minor dim of
#: every block to be a multiple of 8 sublanes; the q40 scale planes have one
#: row per 64 input rows (8 * 64 = 512) and the q80 plane one per 32
#: (8 * 32 = 256). Packing pads K up to this, with zero scales in the pad
#: region and zero-padded activation rows at call time, so the padding
#: contributes exactly 0 to every dot product. Without this, shapes like
#: Llama-2-7B's hidden 11008 (divisible by 256, not 512) force a (4, bo)
#: scale block and crash Mosaic — the round-2 bench failure.
K_MULTIPLE = {"q40": 512, "q80": 256}


def _pad_up(n: int, multiple: int) -> int:
    return (n + multiple - 1) // multiple * multiple


def _pad_rows(x: jnp.ndarray, multiple: int = 8) -> tuple[jnp.ndarray, int]:
    """Pad the leading (token) dim up to a sublane multiple."""
    t = x.shape[0]
    tp = max(multiple, (t + multiple - 1) // multiple * multiple)
    if tp != t:
        x = jnp.pad(x, ((0, tp - t), (0, 0)))
    return x, t


#: token-row block: decode (T <= 8) runs one t-block; big prefill batches tile
#: so the x / out tiles stay a bounded slice of VMEM (a 2048-token prefill
#: with whole-T blocks would need ~16 MB for x + out alone). t is OUTERMOST in
#: the grid so the out block still accumulates over the innermost k sweep;
#: weights re-stream once per t-block, which large-T prefill (MXU-bound)
#: amortizes. The t grid is ragged like o: token rows are independent.
T_BLOCK = 256


def _pad_cols(x: jnp.ndarray, k_padded: int) -> jnp.ndarray:
    """Zero-pad the input-feature dim of activations up to the packed K."""
    if x.shape[1] != k_padded:
        x = jnp.pad(x, ((0, 0), (0, k_padded - x.shape[1])))
    return x


def tile_plan(kind: str, k_padded: int, out_features: int) -> tuple[int, int]:
    """The (bk, bo) grid block sizes the kernels use for a packed matrix.

    The O grid is ragged — ``ceil(O / bo)`` blocks with Mosaic masking the
    boundary block's stores — so bo never shrinks to fit an awkward O. This
    matters enormously for decode throughput: Llama-2-7B's hidden dim 11008
    only divides by 256, and a (43, 4)-step grid of tiny tiles ran the kernel
    at ~280 GB/s effective; full 1024-lane tiles reach ~500+ GB/s on the same
    shape (measured on v5e, scripts/kernel_bench.py). Raggedness is safe on
    the O axis only: each output column depends on exactly its own weight
    column, so boundary-block garbage lands in masked-out columns. The K axis
    by contrast is contracted, so bk MUST divide k_padded exactly (pack_q40 /
    pack_q80 pad K to K_MULTIPLE, and every candidate here divides it).

    Invariant (asserted by tests/test_qmatmul.py over the real model shapes):
    every operand block satisfies Mosaic's (8, 128) tiling — in particular the
    scale planes, whose sublane count is bk/64 (q40) or bk/32 (q80)."""
    if k_padded % K_MULTIPLE[kind] != 0:
        raise ValueError(
            f"{kind} packed input dim {k_padded} is not a multiple of "
            f"{K_MULTIPLE[kind]} — build QuantTensors via pack_q40/pack_q80, "
            "which pad K so every Mosaic block satisfies (8, 128) tiling"
        )
    if out_features < 128:
        bo = out_features  # toy dims (interpret-mode tests): one lane tile
    else:
        bo = min(1024, _pad_up(out_features, 128))
    align = K_MULTIPLE[kind]  # keeps the scale planes at >= 8 sublanes
    for bk in sorted({k_padded, k_padded // 2, 8192, 4096, 2048, 1024,
                      512, 256}, reverse=True):
        if bk and k_padded % bk == 0 and bk % align == 0 \
                and bk * bo <= _TILE_CELL_CAP:
            return bk, bo
    # unreachable: bk = K_MULTIPLE[kind] always divides k_padded (the
    # precondition above), is self-aligned, and 512 * 1024 < _TILE_CELL_CAP
    raise AssertionError(f"no valid bk for {kind} k_padded={k_padded} bo={bo}")


# ---------------------------------------------------------------------------
# Q80: int8 weights, one f32 scale per 32 input rows
# ---------------------------------------------------------------------------

def _q80_kernel(*refs, acc_dtype, stacked=False, fuse_norm=False):
    from jax.experimental import pallas as pl

    if stacked:  # scalar-prefetch layout: leading layer axis, idx_ref first
        refs = refs[1:]
        x_ref, w_ref, s_ref, *refs = refs
        wq, s = w_ref[0], s_ref[0]
    else:
        x_ref, w_ref, s_ref, *refs = refs
        wq, s = w_ref[...], s_ref[...]
    if fuse_norm:  # rmsnorm epilogue operands: [bt, 1] inv, [1, bk] weight
        inv_ref, nw_ref, o_ref = refs
    else:
        (o_ref,) = refs

    @pl.when(pl.program_id(2) == 0)  # grid (t, o, k): init at each k sweep
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    if fuse_norm:
        # exactly ops.norms.rmsnorm's elementwise tail — f32 product order
        # weight * (x * inv), cast once to bf16 — so the fused activation
        # tile is bit-identical to the unfused rmsnorm's output
        nw = nw_ref[0] if stacked else nw_ref[...]  # drop the layer axis
        x = (nw * (x_ref[...].astype(jnp.float32) * inv_ref[...])
             ).astype(jnp.bfloat16)
    else:
        x = x_ref[...]
    w = wq.astype(jnp.int32).astype(jnp.float32)  # [bk, bo]
    bk, bo = w.shape
    scale = jnp.reshape(
        jnp.broadcast_to(s[:, None, :], (bk // QK, QK, bo)), (bk, bo)
    )
    wd = (w * scale).astype(jnp.bfloat16)
    o_ref[...] += jnp.dot(x, wd, preferred_element_type=acc_dtype)


def _norm_operands(norm_w, norm_inv, k_padded):
    """Pad the fused-rmsnorm epilogue operands to kernel layout: the norm
    weight as a [1, k_padded] f32 plane (zero pad cols, so padded activation
    columns stay exactly 0 after the in-kernel epilogue) and the
    ``rmsnorm_inv`` normalizer row-padded like the activations."""
    nw = norm_w.astype(jnp.float32)
    if nw.shape[-1] != k_padded:
        pad = [(0, 0)] * (nw.ndim - 1) + [(0, k_padded - nw.shape[-1])]
        nw = jnp.pad(nw, pad)
    nw = nw[..., None, :]  # [1, K] flat | [L, 1, K] layer-stacked
    inv_p, _ = _pad_rows(norm_inv)
    return nw, inv_p


def _norm_layer_map(norm_w):
    """Plane selector for the stacked kernels' norm-weight index_map: the
    scalar-prefetched layer for a stacked [L, K] weight, plane 0 for a
    flat [K] weight the caller already sliced (llama's scan body)."""
    if norm_w.ndim == 2:
        return lambda idx: idx[0]
    return lambda idx: 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def q80_matmul(x: jnp.ndarray, w: jnp.ndarray, scales: jnp.ndarray,
               interpret: bool | None = None,
               norm_w: jnp.ndarray | None = None,
               norm_inv: jnp.ndarray | None = None) -> jnp.ndarray:
    """``x [T, K] @ dequant(w int8 [K, O], scales [K/32, O]) -> [T, O]`` f32.

    ``norm_w``/``norm_inv`` (both or neither): fuse the rmsnorm epilogue
    into the kernel's t-block — x arrives RAW and each tile is normalized
    in VMEM (``norm_w [K]`` f32, ``norm_inv = rmsnorm_inv(x, eps) [T, 1]``),
    bit-identical to ``q80_matmul(rmsnorm(x, norm_w), ...)`` while never
    materializing the normalized activation in HBM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    fused = norm_w is not None
    K, O = w.shape  # K is the *packed* (padded) input dim
    # fused: keep x's own dtype (the epilogue normalizes in f32 from the raw
    # activation, exactly like rmsnorm) — bf16 only for the plain kernel
    xp, t = _pad_rows(_pad_cols(x if fused else x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    bk, bo = tile_plan("q80", K, O)
    bt = min(T, T_BLOCK)
    in_specs = [
        pl.BlockSpec((bt, bk), lambda t_, o, k: (t_, k)),
        pl.BlockSpec((bk, bo), lambda t_, o, k: (k, o)),
        pl.BlockSpec((bk // QK, bo), lambda t_, o, k: (k, o)),
    ]
    operands = [xp, w, scales]
    if fused:
        nw, inv_p = _norm_operands(norm_w, norm_inv, K)
        in_specs += [
            pl.BlockSpec((bt, 1), lambda t_, o, k: (t_, 0)),  # dllama: allow[PALLAS-001] reason=whole-array lane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, bk), lambda t_, o, k: (0, k)),  # dllama: allow[PALLAS-001] reason=whole-array sublane dim (proven: tests/test_lowering.py sweep)
        ]
        operands += [inv_p, nw]
    out = pl.pallas_call(
        functools.partial(_q80_kernel, acc_dtype=jnp.float32,
                          fuse_norm=fused),
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k: (t_, o)),
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*operands)
    return out[:t]


@functools.partial(jax.jit, static_argnames=("interpret",))
def q80_matmul_stacked(x: jnp.ndarray, w: jnp.ndarray, scales: jnp.ndarray,
                       layer: jnp.ndarray,
                       interpret: bool | None = None,
                       norm_w: jnp.ndarray | None = None,
                       norm_inv: jnp.ndarray | None = None) -> jnp.ndarray:
    """Layer-indexed ``x [T, K] @ dequant(w[layer])`` over STACKED planes
    ``w int8 [L, K, O]``, ``scales [L, K/32, O]``, with a traced ``layer``.

    Why this exists: the decode forward scans over layers. If the scan body
    sliced the stacked planes (``w[idx]``) before calling the kernel, XLA
    would have to MATERIALIZE each layer's slice every step — a Pallas
    custom-call operand can't fuse a dynamic-slice — tripling the per-token
    HBM traffic (read + write the copy, then read it again in the kernel).
    Instead the whole stacked plane is the operand and a scalar-prefetched
    layer index steers the kernel's own DMA via the BlockSpec index_map, so
    each layer's bytes are read from HBM exactly once, in place."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    fused = norm_w is not None
    _, K, O = w.shape
    xp, t = _pad_rows(_pad_cols(x if fused else x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    bk, bo = tile_plan("q80", K, O)
    bt = min(T, T_BLOCK)
    in_specs = [
        pl.BlockSpec((bt, bk), lambda t_, o, k, idx: (t_, k)),
        pl.BlockSpec((1, bk, bo), lambda t_, o, k, idx: (idx[0], k, o)),
        pl.BlockSpec((1, bk // QK, bo),
                     lambda t_, o, k, idx: (idx[0], k, o)),
    ]
    operands = [xp, w, scales]
    if fused:
        # norm weight: layer-stacked [L, K] (kernel indexes plane idx[0]) or
        # already-sliced flat [K] (the scan body's lp dict — plane 0)
        lsel = _norm_layer_map(norm_w)
        nw, inv_p = _norm_operands(
            norm_w if norm_w.ndim == 2 else norm_w[None], norm_inv, K)
        in_specs += [
            pl.BlockSpec((bt, 1), lambda t_, o, k, idx: (t_, 0)),  # dllama: allow[PALLAS-001] reason=whole-array lane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, 1, bk), lambda t_, o, k, idx: (lsel(idx), 0, k)),  # dllama: allow[PALLAS-001] reason=whole-array sublane dim (proven: tests/test_lowering.py sweep)
        ]
        operands += [inv_p, nw]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k, idx: (t_, o)),
    )
    out = pl.pallas_call(
        functools.partial(_q80_kernel, acc_dtype=jnp.float32, stacked=True,
                          fuse_norm=fused),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), *operands)
    return out[:t]


# ---------------------------------------------------------------------------
# Q40: packed nibbles, two scale planes (even/odd 32-blocks)
# ---------------------------------------------------------------------------

def _q40_kernel(*refs, acc_dtype, stacked=False, nosub=False, fuse_norm=False):
    from jax.experimental import pallas as pl

    if stacked:  # scalar-prefetch layout: leading layer axis, idx_ref first
        refs = refs[1:]
        xlo_ref, xhi_ref, w_ref, slo_ref, shi_ref, *refs = refs
        pk8, slo, shi = w_ref[0], slo_ref[0], shi_ref[0]
    else:
        xlo_ref, xhi_ref, w_ref, slo_ref, shi_ref, *refs = refs
        pk8, slo, shi = w_ref[...], slo_ref[...], shi_ref[...]
    if fuse_norm:  # rmsnorm epilogue: [bt, 1] inv + split norm-weight planes
        inv_ref, nwlo_ref, nwhi_ref, o_ref = refs
    else:
        (o_ref,) = refs

    @pl.when(pl.program_id(2) == 0)  # grid (t, o, k): init at each k sweep
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    if fuse_norm:  # same f32 order as ops.norms.rmsnorm -> bit-identical
        inv = inv_ref[...]
        nwlo = nwlo_ref[0] if stacked else nwlo_ref[...]  # drop layer axis
        nwhi = nwhi_ref[0] if stacked else nwhi_ref[...]
        xlo = (nwlo * (xlo_ref[...].astype(jnp.float32) * inv)
               ).astype(jnp.bfloat16)
        xhi = (nwhi * (xhi_ref[...].astype(jnp.float32) * inv)
               ).astype(jnp.bfloat16)
    else:
        xlo, xhi = xlo_ref[...], xhi_ref[...]
    pk = pk8.astype(jnp.int32)  # [bk/2, bo]
    hk, bo = pk.shape
    # nosub drops the nibble recentering (the binding VPU op); the caller
    # subtracts the exact 8 * blocksum(x) * delta correction outside
    lo = (pk & 0xF).astype(jnp.float32)
    hi = ((pk >> 4) & 0xF).astype(jnp.float32)
    if not nosub:
        lo = lo - 8.0
        hi = hi - 8.0
    nsb = slo.shape[0]  # bk/64 superblocks in this tile
    s_lo = jnp.reshape(
        jnp.broadcast_to(slo[:, None, :], (nsb, QK, bo)), (hk, bo)
    )
    s_hi = jnp.reshape(
        jnp.broadcast_to(shi[:, None, :], (nsb, QK, bo)), (hk, bo)
    )
    w_lo = (lo * s_lo).astype(jnp.bfloat16)
    w_hi = (hi * s_hi).astype(jnp.bfloat16)
    o_ref[...] += jnp.dot(xlo, w_lo, preferred_element_type=acc_dtype)
    o_ref[...] += jnp.dot(xhi, w_hi, preferred_element_type=acc_dtype)


def _q40_corr_kernel(*refs):
    """8 * (blocksums(x) @ scale planes) — the exact recentering term the
    nosub kernel omits. Tiny MXU dots (contraction dim = K/64); the scale
    planes are re-read from HBM (+~20% of the q40 bytes), a trade the VPU
    savings win back several times over (see Q40_NOSUB)."""
    if len(refs) == 6:  # stacked: scalar-prefetch layer index first
        _idx_ref, xslo_ref, xshi_ref, slo_ref, shi_ref, o_ref = refs
        slo, shi = slo_ref[0], shi_ref[0]
    else:
        xslo_ref, xshi_ref, slo_ref, shi_ref, o_ref = refs
        slo, shi = slo_ref[...], shi_ref[...]
    o_ref[...] = 8.0 * (
        jnp.dot(xslo_ref[...], slo, preferred_element_type=jnp.float32)
        + jnp.dot(xshi_ref[...], shi, preferred_element_type=jnp.float32)
    )


def _q40_block_sums(xp: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-32-block activation sums, split into the even/odd planes matching
    the packed nibble layout (even 32-block = low nibble / s plane, odd =
    high nibble / s2 plane). xp is the padded [T, K] activation."""
    T, K = xp.shape
    xs = xp.astype(jnp.float32).reshape(T, K // QK, QK).sum(-1)
    return xs[:, 0::2], xs[:, 1::2]  # each [T, K/64]


def _q40_correction(xp, s_lo, s_hi, layer=None, interpret=False):
    """Run the correction kernel. ``s_lo/s_hi`` are [K/64, O] (or stacked
    [L, K/64, O] with a traced ``layer``); returns [T, O] f32. A Pallas
    kernel — not two jnp dots — so the stacked case steers the layer choice
    through the scalar-prefetched index_map instead of materializing a
    dynamic-slice of the scale planes every scan step."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    xs_lo, xs_hi = _q40_block_sums(xp)
    T, NS = xs_lo.shape
    O = s_lo.shape[-1]
    bo = O if O < 128 else min(1024, _pad_up(O, 128))
    bt = min(T, T_BLOCK)
    params = compat.tpu_compiler_params(
        dimension_semantics=("parallel", "parallel"))
    if layer is None:
        return pl.pallas_call(
            _q40_corr_kernel,
            grid=(pl.cdiv(T, bt), pl.cdiv(O, bo)),
            in_specs=[
                pl.BlockSpec((bt, NS), lambda t_, o: (t_, 0)),
                pl.BlockSpec((bt, NS), lambda t_, o: (t_, 0)),
                pl.BlockSpec((NS, bo), lambda t_, o: (0, o)),
                pl.BlockSpec((NS, bo), lambda t_, o: (0, o)),
            ],
            out_specs=pl.BlockSpec((bt, bo), lambda t_, o: (t_, o)),
            out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
            compiler_params=params,
            interpret=interpret,
        )(xs_lo, xs_hi, s_lo, s_hi)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo)),
        in_specs=[
            pl.BlockSpec((bt, NS), lambda t_, o, idx: (t_, 0)),
            pl.BlockSpec((bt, NS), lambda t_, o, idx: (t_, 0)),
            pl.BlockSpec((1, NS, bo), lambda t_, o, idx: (idx[0], 0, o)),
            pl.BlockSpec((1, NS, bo), lambda t_, o, idx: (idx[0], 0, o)),
        ],
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, idx: (t_, o)),
    )
    return pl.pallas_call(
        _q40_corr_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=params,
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), xs_lo, xs_hi, s_lo, s_hi)


def _q40_split(xp: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[T, K] -> the lo/hi 32-row halves of each 64-row superblock (the
    packed-nibble pairing), each [T, K/2] — a pure reshape."""
    T, K = xp.shape
    xr = xp.reshape(T, K // 64, 64)
    return xr[:, :, :QK].reshape(T, K // 2), xr[:, :, QK:].reshape(T, K // 2)


def _q40_normed(xp, norm_w, norm_inv, layer=None):
    """The normalized padded activation the fused q40 kernel computes in its
    tiles, materialized OUTSIDE for the nosub correction's block sums only
    (an elementwise+reduce XLA fuses; [T, K/64] output, no [T, K] HBM
    round-trip). Must match the in-kernel epilogue bit-for-bit."""
    nw = norm_w[layer] if (layer is not None and norm_w.ndim == 2) else norm_w
    nw = nw.astype(jnp.float32)
    if nw.shape[-1] != xp.shape[-1]:
        nw = jnp.pad(nw, (0, xp.shape[-1] - nw.shape[-1]))
    inv_p, _ = _pad_rows(norm_inv)
    return (nw * (xp.astype(jnp.float32) * inv_p)).astype(jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("interpret", "nosub"))
def q40_matmul(x: jnp.ndarray, packed: jnp.ndarray, s_lo: jnp.ndarray,
               s_hi: jnp.ndarray, interpret: bool | None = None,
               nosub: bool | None = None,
               norm_w: jnp.ndarray | None = None,
               norm_inv: jnp.ndarray | None = None) -> jnp.ndarray:
    """``x [T, K] @ dequant(packed uint8 [K/2, O]) -> [T, O]`` f32.

    ``norm_w``/``norm_inv``: fused rmsnorm epilogue (see ``q80_matmul``) —
    the norm weight rides split into the same lo/hi half-superblock planes
    as the activations."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    if nosub is None:
        nosub = Q40_NOSUB
    fused = norm_w is not None
    O = packed.shape[1]
    K = packed.shape[0] * 2  # the *packed* (padded) input dim
    xp, t = _pad_rows(_pad_cols(x if fused else x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    # split activations into the lo/hi 32-row halves of each 64-row superblock
    x_lo, x_hi = _q40_split(xp)
    bk, bo = tile_plan("q40", K, O)
    bt = min(T, T_BLOCK)
    in_specs = [
        pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
        pl.BlockSpec((bt, bk // 2), lambda t_, o, k: (t_, k)),
        pl.BlockSpec((bk // 2, bo), lambda t_, o, k: (k, o)),
        pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
        pl.BlockSpec((bk // 64, bo), lambda t_, o, k: (k, o)),
    ]
    operands = [x_lo, x_hi, packed, s_lo, s_hi]
    if fused:
        nw, inv_p = _norm_operands(norm_w, norm_inv, K)
        nw_lo, nw_hi = _q40_split(nw)
        in_specs += [
            pl.BlockSpec((bt, 1), lambda t_, o, k: (t_, 0)),  # dllama: allow[PALLAS-001] reason=whole-array lane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, bk // 2), lambda t_, o, k: (0, k)),  # dllama: allow[PALLAS-001] reason=whole-array sublane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, bk // 2), lambda t_, o, k: (0, k)),  # dllama: allow[PALLAS-001] reason=whole-array sublane dim (proven: tests/test_lowering.py sweep)
        ]
        operands += [inv_p, nw_lo, nw_hi]
    out = pl.pallas_call(
        functools.partial(_q40_kernel, acc_dtype=jnp.float32, nosub=nosub,
                          fuse_norm=fused),
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k: (t_, o)),
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*operands)
    if nosub:
        xn = _q40_normed(xp, norm_w, norm_inv) if fused else xp
        out = out - _q40_correction(xn, s_lo, s_hi, interpret=interpret)
    return out[:t]


@functools.partial(jax.jit, static_argnames=("interpret", "nosub"))
def q40_matmul_stacked(x: jnp.ndarray, packed: jnp.ndarray, s_lo: jnp.ndarray,
                       s_hi: jnp.ndarray, layer: jnp.ndarray,
                       interpret: bool | None = None,
                       nosub: bool | None = None,
                       norm_w: jnp.ndarray | None = None,
                       norm_inv: jnp.ndarray | None = None) -> jnp.ndarray:
    """Layer-indexed q40 matmul over STACKED planes ``packed uint8 [L, K/2,
    O]`` with a traced ``layer`` — see ``q80_matmul_stacked`` for why the
    layer selection must happen inside the kernel's index_map. ``norm_w``
    ([L, K] stacked) / ``norm_inv``: fused rmsnorm epilogue."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _interpret_default()
    if nosub is None:
        nosub = Q40_NOSUB
    fused = norm_w is not None
    O = packed.shape[2]
    K = packed.shape[1] * 2
    xp, t = _pad_rows(_pad_cols(x if fused else x.astype(jnp.bfloat16), K))
    T = xp.shape[0]
    x_lo, x_hi = _q40_split(xp)
    bk, bo = tile_plan("q40", K, O)
    bt = min(T, T_BLOCK)
    in_specs = [
        pl.BlockSpec((bt, bk // 2), lambda t_, o, k, idx: (t_, k)),
        pl.BlockSpec((bt, bk // 2), lambda t_, o, k, idx: (t_, k)),
        pl.BlockSpec((1, bk // 2, bo), lambda t_, o, k, idx: (idx[0], k, o)),
        pl.BlockSpec((1, bk // 64, bo), lambda t_, o, k, idx: (idx[0], k, o)),
        pl.BlockSpec((1, bk // 64, bo), lambda t_, o, k, idx: (idx[0], k, o)),
    ]
    operands = [x_lo, x_hi, packed, s_lo, s_hi]
    if fused:  # norm weight [L, K] stacked | flat [K] -> split lo/hi planes
        lsel = _norm_layer_map(norm_w)
        nw, inv_p = _norm_operands(
            norm_w if norm_w.ndim == 2 else norm_w[None], norm_inv, K)
        L = nw.shape[0]
        nw_lo, nw_hi = _q40_split(nw.reshape(L, K))
        nw_lo, nw_hi = nw_lo[:, None, :], nw_hi[:, None, :]  # [L, 1, K/2]
        in_specs += [
            pl.BlockSpec((bt, 1), lambda t_, o, k, idx: (t_, 0)),  # dllama: allow[PALLAS-001] reason=whole-array lane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, 1, bk // 2), lambda t_, o, k, idx: (lsel(idx), 0, k)),  # dllama: allow[PALLAS-001] reason=whole-array sublane dim (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, 1, bk // 2), lambda t_, o, k, idx: (lsel(idx), 0, k)),  # dllama: allow[PALLAS-001] reason=whole-array sublane dim (proven: tests/test_lowering.py sweep)
        ]
        operands += [inv_p, nw_lo, nw_hi]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(pl.cdiv(T, bt), pl.cdiv(O, bo), K // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bt, bo), lambda t_, o, k, idx: (t_, o)),
    )
    out = pl.pallas_call(
        functools.partial(_q40_kernel, acc_dtype=jnp.float32, stacked=True,
                          nosub=nosub, fuse_norm=fused),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, O), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), *operands)
    if nosub:
        xn = (_q40_normed(xp, norm_w, norm_inv, layer=layer) if fused
              else xp)
        out = out - _q40_correction(xn, s_lo, s_hi, layer=layer,
                                    interpret=interpret)
    return out[:t]


# ---------------------------------------------------------------------------
# QuantTensor: the weight-pytree leaf for quantized matrices
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclass
class QuantTensor:
    """A [in, out] matrix stored block-quantized for the fused kernels.

    ``kind`` is static metadata ("q40" | "q80"). For q40, ``w`` is the packed
    uint8 plane and ``s2`` the second (odd-block) scale plane; for q80, ``w``
    is int8 and ``s2`` is an empty placeholder (pytree leaves must be arrays).
    Works stacked: a leading layer axis on every field makes it scannable.

    ``k_logical`` is the pre-padding input dim (0 = no padding; see
    ``K_MULTIPLE``). The padded tail rows multiply zero-padded activation
    rows, so every matmul result is exact for the logical shape.
    """

    w: jnp.ndarray
    s: jnp.ndarray
    s2: jnp.ndarray
    kind: str = field(metadata=dict(static=True), default="q40")
    k_logical: int = field(metadata=dict(static=True), default=0)

    @property
    def k_padded(self) -> int:
        return self.w.shape[-2] * (2 if self.kind == "q40" else 1)

    @property
    def in_features(self) -> int:
        return self.k_logical or self.k_padded

    @property
    def out_features(self) -> int:
        return self.w.shape[-1]


def qmatmul(x: jnp.ndarray, qt: QuantTensor, layer=None) -> jnp.ndarray:
    """Dispatch ``x @ dequant(qt)`` to the right fused kernel. Output dtype
    follows ``x`` (the caller's activation dtype), accumulation is f32.

    ``layer``: a traced int32 selecting one layer of a layer-STACKED
    QuantTensor (planes with a leading L axis) — the scalar-prefetch path
    used by the scan-over-layers forward. None = qt is a single matrix."""
    if qt.kind == "q40":
        if layer is None:
            out = q40_matmul(x, qt.w, qt.s, qt.s2)
        else:
            out = q40_matmul_stacked(x, qt.w, qt.s, qt.s2, layer)
    elif qt.kind == "q80":
        if layer is None:
            out = q80_matmul(x, qt.w, qt.s)
        else:
            out = q80_matmul_stacked(x, qt.w, qt.s, layer)
    else:
        raise ValueError(f"unknown QuantTensor kind {qt.kind!r}")
    return out.astype(x.dtype)


def qmatmul_norm(x: jnp.ndarray, norm_w: jnp.ndarray, qt: QuantTensor,
                 layer=None, eps: float = 1e-5) -> jnp.ndarray:
    """``rmsnorm(x, norm_w) @ dequant(qt)`` with the norm fused into the
    matmul kernel as an x-block epilogue (DLLAMA_FUSE_NORM): the raw
    activation streams into VMEM once and the normalized bf16 tile is
    produced in-register, eliminating the separate rmsnorm HBM round trip.
    Bit-identical to the unfused composition — same f32 op order, same final
    bf16 cast (tests/test_fused_ops.py). ``norm_w`` is ``[K]`` flat or
    ``[L, K]`` when ``layer`` selects a layer of a stacked QuantTensor."""
    inv = rmsnorm_inv(x, eps)
    if qt.kind == "q40":
        if layer is None:
            out = q40_matmul(x, qt.w, qt.s, qt.s2, norm_w=norm_w,
                             norm_inv=inv)
        else:
            out = q40_matmul_stacked(x, qt.w, qt.s, qt.s2, layer,
                                     norm_w=norm_w, norm_inv=inv)
    elif qt.kind == "q80":
        if layer is None:
            out = q80_matmul(x, qt.w, qt.s, norm_w=norm_w, norm_inv=inv)
        else:
            out = q80_matmul_stacked(x, qt.w, qt.s, layer, norm_w=norm_w,
                                     norm_inv=inv)
    else:
        raise ValueError(f"unknown QuantTensor kind {qt.kind!r}")
    return out.astype(x.dtype)


def matmul_any(x: jnp.ndarray, w, layer=None) -> jnp.ndarray:
    """``x @ w`` where w is a plain array or a QuantTensor. ``layer`` selects
    a layer of a stacked QuantTensor (ignored for plain arrays, which the
    caller indexes itself — XLA fuses a dense dynamic-slice into the dot)."""
    if isinstance(w, QuantTensor):
        return qmatmul(x, w, layer)
    return x @ w


def slice_to_in_features(h: jnp.ndarray, w) -> jnp.ndarray:
    """Trim a gathered activation down to ``w``'s (packed) input width.

    Under quantized TP the up-projections lane-pad their output axis
    (parallel.quant_tp); when the matching down-projection took the dense
    fallback (its input not packable) the gathered hidden is wider than the
    matrix expects — the pad columns are exact zeros, so dropping them is
    exact. No-op when the widths already agree."""
    w_in = w.k_padded if isinstance(w, QuantTensor) else w.shape[-2]
    return h[..., :w_in] if h.shape[-1] > w_in else h


# ---------------------------------------------------------------------------
# Packing (host-side, numpy)
# ---------------------------------------------------------------------------

def pack_q40(quants: np.ndarray, deltas: np.ndarray,
             to_device: bool = True) -> QuantTensor:
    """Build the kernel layout from unpacked quants ``int [K, O]`` in -8..7
    and per-block deltas ``[K/32, O]`` (block = 32 consecutive input rows).
    K is padded up to ``K_MULTIPLE['q40']`` (zero quants + zero scales) so the
    kernel's scale-plane blocks always satisfy Mosaic's 8-sublane tiling.

    ``to_device=False`` keeps the planes as host numpy arrays — the streaming
    sharded loader stacks layers on host and places the stacked tensor
    directly into its mesh sharding, so no single device ever holds the whole
    model (parallel.quant_tp)."""
    K, O = quants.shape
    assert K % 64 == 0, f"q40 kernel needs in_features % 64 == 0, got {K}"
    kp = _pad_up(K, K_MULTIPLE["q40"])
    if kp != K:
        quants = np.concatenate(
            [quants, np.zeros((kp - K, O), quants.dtype)], axis=0
        )
        deltas = np.concatenate(
            [deltas, np.zeros(((kp - K) // QK, O), np.float32)], axis=0
        )
    u = (quants.astype(np.int16) + 8).astype(np.uint8)
    ur = u.reshape(kp // 64, 2, QK, O)
    packed = (ur[:, 0] | (ur[:, 1] << 4)).reshape(kp // 2, O)
    d = deltas.astype(np.float32).reshape(kp // 64, 2, O)
    put = jnp.asarray if to_device else np.ascontiguousarray
    return QuantTensor(
        w=put(packed), s=put(d[:, 0].copy()),
        s2=put(d[:, 1].copy()), kind="q40", k_logical=K,
    )


def pack_q80(quants: np.ndarray, deltas: np.ndarray,
             to_device: bool = True) -> QuantTensor:
    """int8 quants [K, O] + per-block deltas [K/32, O] -> kernel layout.
    K is padded up to ``K_MULTIPLE['q80']`` like ``pack_q40``."""
    K, O = quants.shape
    assert K % QK == 0
    kp = _pad_up(K, K_MULTIPLE["q80"])
    if kp != K:
        quants = np.concatenate(
            [quants, np.zeros((kp - K, O), quants.dtype)], axis=0
        )
        deltas = np.concatenate(
            [deltas, np.zeros(((kp - K) // QK, O), np.float32)], axis=0
        )
    put = jnp.asarray if to_device else np.ascontiguousarray
    return QuantTensor(
        w=put(quants.astype(np.int8)),
        s=put(deltas.astype(np.float32)),
        s2=put(np.zeros((0,), np.float32)), kind="q80", k_logical=K,
    )


def quantize_tensor(w: np.ndarray, kind: str, to_device: bool = True) -> QuantTensor:
    """Quantize a dense ``[K, O]`` f32 matrix with the reference's block math
    (`/root/reference/converter/writer.py:26-75`), blocks along K."""
    w = np.ascontiguousarray(w, np.float32)
    K, O = w.shape
    # blocks run down the input dim: quantize the transposed rows
    flat = np.ascontiguousarray(w.T).reshape(-1)  # [O*K], rows of K
    if kind == "q40":
        raw = blocks.quantize_q40(flat)
        q, d = blocks.unpack_q40(raw)  # [O*K/32, 32], [O*K/32]
        q = q.reshape(O, K).T  # [K, O]
        d = d.reshape(O, K // QK).T  # [K/32, O]
        return pack_q40(q, d, to_device)
    if kind == "q80":
        raw = blocks.quantize_q80(flat)
        q, d = blocks.unpack_q80(raw)
        return pack_q80(q.reshape(O, K).T, d.reshape(O, K // QK).T, to_device)
    raise ValueError(f"unknown quant kind {kind!r}")


def repack_q40(raw: np.ndarray, d: int, n: int, to_device: bool = True) -> QuantTensor:
    """Losslessly repack a reference-format Q40 tensor (``d`` rows of ``n``
    values, blocks along n — `/root/reference/src/quants.hpp:16-19`) into the
    kernel layout for the transposed ``[n, d]`` kernel matrix."""
    q, deltas = blocks.unpack_q40(raw)  # [d*n/32, 32] in -8..7, [d*n/32]
    q = q.reshape(d, n).T  # [n, d] = [K, O]
    deltas = deltas.reshape(d, n // QK).T  # [K/32, O]
    return pack_q40(q, deltas, to_device)


def repack_q80(raw: np.ndarray, d: int, n: int, to_device: bool = True) -> QuantTensor:
    q, deltas = blocks.unpack_q80(raw)
    return pack_q80(q.reshape(d, n).T, deltas.reshape(d, n // QK).T, to_device)


def dequantize(qt: QuantTensor) -> np.ndarray:
    """QuantTensor -> dense f32 [K, O] at the *logical* K (padding stripped;
    reference semantics, for tests)."""
    if qt.kind == "q80":
        q = np.asarray(qt.w, np.float32)
        s = np.repeat(np.asarray(qt.s, np.float32), QK, axis=-2)
        dense = q * s
    else:
        pk = np.asarray(qt.w)
        half, O = pk.shape[-2:]
        lo = (pk & 0xF).astype(np.float32) - 8.0
        hi = ((pk >> 4) & 0xF).astype(np.float32) - 8.0
        s_lo = np.repeat(np.asarray(qt.s, np.float32), QK, axis=-2)
        s_hi = np.repeat(np.asarray(qt.s2, np.float32), QK, axis=-2)
        dq_lo = (lo * s_lo).reshape(*pk.shape[:-2], half // QK, QK, O)
        dq_hi = (hi * s_hi).reshape(*pk.shape[:-2], half // QK, QK, O)
        dense = np.concatenate([dq_lo, dq_hi], axis=-2).reshape(
            *pk.shape[:-2], half * 2, O
        )
    return dense[..., : qt.in_features, :]
