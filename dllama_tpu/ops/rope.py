"""Rotary position embeddings — both conventions the reference supports.

* **interleaved** (Llama archs): pair ``(2j, 2j+1)`` within each head, angle
  ``pos * theta^(-2j/head_size)`` — matches ``LlamaRopeSlice``
  (`/root/reference/src/transformer.cpp:98-135`) and the HF->interleaved permute
  the reference converter applies (`/root/reference/converter/convert-hf.py:12-15`).
* **half** (Grok-1 / Mixtral, a.k.a. NeoX/Falcon layout): pair
  ``(j, j + head_size/2)``, same angles — matches ``FalconRopeSlice``
  (`/root/reference/src/transformer.cpp:137-159`).

Tables are precomputed once per model as f32 ``[seq_len, head_size//2]`` and the
rotation itself runs in f32 (the reference computes RoPE on f32 activations).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

INTERLEAVED = "interleaved"
HALF = "half"


def rope_table(seq_len: int, head_size: int, theta: float) -> tuple[np.ndarray, np.ndarray]:
    """(cos, sin) tables, each [seq_len, head_size//2], f32."""
    j = np.arange(0, head_size, 2, dtype=np.float32)  # 2j over the head
    freqs = 1.0 / np.power(np.float32(theta), j / np.float32(head_size))
    angles = np.arange(seq_len, dtype=np.float32)[:, None] * freqs[None, :]
    return np.cos(angles), np.sin(angles)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, style: str = INTERLEAVED
) -> jnp.ndarray:
    """Rotate ``x [..., n_heads, head_size]`` with per-position tables.

    ``cos``/``sin`` must be broadcastable to ``[..., 1, head_size//2]`` — pass
    ``table[pos]`` (decode, one position) or ``table[pos:pos+T, None, :]`` (prefill).
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    c = cos.astype(jnp.float32)
    s = sin.astype(jnp.float32)
    if style == INTERLEAVED:
        x0 = xf[..., 0::2]
        x1 = xf[..., 1::2]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        out = jnp.stack([r0, r1], axis=-1).reshape(xf.shape)
    elif style == HALF:
        half = xf.shape[-1] // 2
        x0 = xf[..., :half]
        x1 = xf[..., half:]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        out = jnp.concatenate([r0, r1], axis=-1)
    else:
        raise ValueError(f"unknown rope style {style!r}")
    return out.astype(dtype)
