"""Static TPU tiling verifier for every Pallas kernel in the inventory.

Mosaic rejects a ``pallas_call`` whose operand blocks violate the tiling
contract: the last two dims of every block must each be **divisible by the
(sublane, lane) = (8, 128) min tile or equal to the corresponding array
dim** (sublane widens to 16 for 2-byte and 32 for 1-byte dtypes). The
round-2 bench hit exactly this on real hardware — the q40 scale plane of
Llama-2-7B's 11008-wide FFN produced a ``(4, 1024)`` block against a
``(172, 4096)`` array and the whole 7B path fell back — and the failure
class is only observable *on* a TPU unless the grid + BlockSpecs are
re-derivable without one.

That is what this module does: ``lowering_plan(kind, shapes)`` reconstructs
every ``pallas_call`` a kernel entry point would launch for the given
logical shapes — same padding, same ``tile_plan``, same BlockSpecs as the
real launch code in ``ops.qmatmul`` / ``ops.flash_decode`` /
``ops.fused_rope_cache`` — and ``verify(plans)`` applies the
divisible-or-whole-dim rule to every block, CPU-only. ``check(...)``
raises ``TilingError`` with the offending kernel + block/array shapes, the
same payload bench.py attaches to a ``pallas_lowering`` trajectory row.

CPU gate: ``tests/test_lowering.py`` sweeps 7B/8B/MoE dims x q40/q80 x
T in {1, 8, 64} (plus f8 caches and the fused variants) so CI catches the
next violation before a hardware window burns. Report:
``python -m dllama_tpu.ops.lowering --json`` dumps the full shape matrix.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

import jax.numpy as jnp

SUBLANE, LANE = 8, 128


class TilingError(ValueError):
    """A block in a planned pallas_call violates Mosaic's tiling rule."""


@dataclass(frozen=True)
class OperandPlan:
    """One operand (or output / scratch buffer) of a planned pallas_call."""

    name: str
    array: tuple  # full array shape
    block: tuple  # BlockSpec block shape ("ANY" memory space -> block == array)
    dtype: str = "float32"

    def violations(self) -> list[str]:
        out = []
        if len(self.block) != len(self.array):
            return [f"{self.name}: block rank {len(self.block)} != "
                    f"array rank {len(self.array)}"]
        if not self.block:
            return out
        itemsize = jnp.dtype(self.dtype).itemsize
        sub = {4: SUBLANE, 2: 16, 1: 32}.get(itemsize, SUBLANE)
        # the contract applies to the last two dims; leading block dims
        # only need to fit inside the array
        checks = []
        if len(self.block) >= 2:
            checks.append((-2, sub, "sublane"))
        checks.append((-1, LANE, "lane"))
        for ax, mult, label in checks:
            b, a = self.block[ax], self.array[ax]
            if b != a and b % mult != 0:
                out.append(
                    f"{self.name}: {label} block dim {b} is neither a "
                    f"multiple of {mult} nor the whole array dim {a} "
                    f"(block {self.block} vs array {self.array}, "
                    f"{self.dtype})")
        for ax in range(len(self.block) - 2):
            if self.block[ax] > self.array[ax]:
                out.append(f"{self.name}: leading block dim {self.block[ax]} "
                           f"exceeds array dim {self.array[ax]}")
        return out


@dataclass(frozen=True)
class KernelPlan:
    """Grid + every operand block of one pallas_call, statically derived."""

    kernel: str
    grid: tuple
    operands: tuple  # of OperandPlan
    note: str = ""

    def violations(self) -> list[str]:
        return [f"{self.kernel}: {v}" for op in self.operands
                for v in op.violations()]

    def to_dict(self) -> dict:
        return {
            "kernel": self.kernel,
            "grid": list(self.grid),
            "operands": [
                {"name": o.name, "array": list(o.array),
                 "block": list(o.block), "dtype": o.dtype}
                for o in self.operands
            ],
            "violations": self.violations(),
        }


def _pad8(n: int) -> int:
    return max(8, (n + 7) // 8 * 8)


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _legacy_tile_plan(kind: str, k_padded: int, out_features: int):
    """The pre-K_MULTIPLE planner (no scale-plane alignment) — kept ONLY so
    the verifier can reconstruct and flag the round-2 failure: feeding the
    raw unpadded 7B hidden dim (11008) yields bk=256 and the infamous
    (4, 1024) scale block against the (172, O) plane."""
    from dllama_tpu.ops.qmatmul import _TILE_CELL_CAP, _pad_up

    bo = out_features if out_features < 128 else min(
        1024, _pad_up(out_features, 128))
    for bk in sorted({k_padded, k_padded // 2, 8192, 4096, 2048, 1024,
                      512, 256}, reverse=True):
        if bk and k_padded % bk == 0 and bk * bo <= _TILE_CELL_CAP:
            return bk, bo
    return k_padded, bo


def _quant_plans(kind: str, shapes: dict) -> list[KernelPlan]:
    """Plans for q40_matmul / q80_matmul (+ stacked variants, + the nosub
    correction kernel, + the fused-norm variants) — mirrors ops.qmatmul."""
    from dllama_tpu.ops import qmatmul as qm

    T = int(shapes.get("T", 1))
    K = int(shapes["K"])
    O = int(shapes["O"])
    L = shapes.get("L")  # not None -> the layer-stacked scalar-prefetch path
    nosub = bool(shapes.get("nosub", kind == "q40" and qm.Q40_NOSUB))
    fused_norm = bool(shapes.get("fused_norm", False))
    kp = int(shapes.get("k_padded") or qm._pad_up(K, qm.K_MULTIPLE[kind]))
    if kp % qm.K_MULTIPLE[kind] == 0:
        bk, bo = qm.tile_plan(kind, kp, O)
    else:
        bk, bo = _legacy_tile_plan(kind, kp, O)
    Tp = _pad8(T)
    bt = min(Tp, qm.T_BLOCK)
    grid = (_cdiv(Tp, bt), _cdiv(O, bo), kp // bk)
    stacked = "_stacked" if L else ""
    fused = "_norm" if fused_norm else ""
    lead = (int(L),) if L else ()
    blead = (1,) if L else ()

    def op(name, arr, blk, dtype="float32"):
        return OperandPlan(name, tuple(arr), tuple(blk), dtype)

    plans = []
    if kind == "q80":
        operands = [
            op("x", (Tp, kp), (bt, bk), "bfloat16"),
            op("w", lead + (kp, O), blead + (bk, bo), "int8"),
            op("scales", lead + (kp // qm.QK, O), blead + (bk // qm.QK, bo)),
        ]
    else:
        operands = [
            op("x_lo", (Tp, kp // 2), (bt, bk // 2), "bfloat16"),
            op("x_hi", (Tp, kp // 2), (bt, bk // 2), "bfloat16"),
            op("w_packed", lead + (kp // 2, O), blead + (bk // 2, bo), "uint8"),
            op("s_lo", lead + (kp // 64, O), blead + (bk // 64, bo)),
            op("s_hi", lead + (kp // 64, O), blead + (bk // 64, bo)),
        ]
    if fused_norm:
        # norm planes: [L, 1, K] for the stacked kernels ([1, 1, K] when the
        # caller pre-sliced a flat [K] weight — same block tiling either way)
        operands.append(op("inv", (Tp, 1), (bt, 1)))
        if kind == "q80":
            operands.append(op("norm_w", lead + (1, kp), blead + (1, bk)))
        else:
            operands.append(
                op("norm_w_lo", lead + (1, kp // 2), blead + (1, bk // 2)))
            operands.append(
                op("norm_w_hi", lead + (1, kp // 2), blead + (1, bk // 2)))
    operands.append(op("out", (Tp, O), (bt, bo)))
    plans.append(KernelPlan(
        kernel=f"{kind}_matmul{stacked}{fused}", grid=grid,
        operands=tuple(operands),
        note=f"T={T} K={K} k_padded={kp} O={O} bk={bk} bo={bo}"))

    if kind == "q40" and nosub:
        NS = kp // 64
        cgrid = (_cdiv(Tp, bt), _cdiv(O, bo))
        plans.append(KernelPlan(
            kernel=f"q40_correction{stacked}", grid=cgrid,
            operands=(
                op("xs_lo", (Tp, NS), (bt, NS)),
                op("xs_hi", (Tp, NS), (bt, NS)),
                op("s_lo", lead + (NS, O), blead + (NS, bo)),
                op("s_hi", lead + (NS, O), blead + (NS, bo)),
                op("out", (Tp, O), (bt, bo)),
            ),
            note=f"nosub correction, NS={NS}"))
    return plans


def _flash_plans(shapes: dict) -> list[KernelPlan]:
    """Plans for flash_decode_attention[_batched] — mirrors
    ops.flash_decode._launch (caches ride memory_space=ANY, so their DMA'd
    VMEM scratch blocks are what the tiling rule constrains)."""
    from dllama_tpu.ops import flash_decode as fd

    T = int(shapes.get("T", 1))
    B = int(shapes.get("B", 1))
    L = int(shapes.get("L", 1))
    S = int(shapes["S"])
    n_heads = int(shapes["n_heads"])
    n_kv = int(shapes.get("n_kv_heads", n_heads))
    hd = int(shapes["head_size"])
    cache_dtype = str(shapes.get("cache_dtype", "bfloat16"))
    batched = B > 1 or bool(shapes.get("batched", False))
    group = n_heads // n_kv
    Tg = (1 if batched else T) * group
    Tgp = _pad8(Tg)
    name = "flash_decode_batched" if batched else "flash_decode"
    ops = (
        OperandPlan("q", (B, n_kv, Tgp, hd), (1, 1, Tgp, hd), "bfloat16"),
        OperandPlan("qpos", (B, Tgp, 1), (1, Tgp, 1), "int32"),
        OperandPlan("k_cache[ANY]", (L, B, S, n_kv, hd), (L, B, S, n_kv, hd),
                    cache_dtype),
        OperandPlan("v_cache[ANY]", (L, B, S, n_kv, hd), (L, B, S, n_kv, hd),
                    cache_dtype),
        OperandPlan("k_buf[scratch]", (2, fd.BLOCK_S, hd), (2, fd.BLOCK_S, hd),
                    cache_dtype),
        OperandPlan("v_buf[scratch]", (2, fd.BLOCK_S, hd), (2, fd.BLOCK_S, hd),
                    cache_dtype),
        OperandPlan("out", (B, n_kv, Tgp, hd), (1, 1, Tgp, hd), "bfloat16"),
    )
    return [KernelPlan(kernel=name, grid=(B, n_kv), operands=ops,
                       note=f"S={S} Tgp={Tgp} cache={cache_dtype}")]


def _rope_cache_plans(shapes: dict) -> list[KernelPlan]:
    """Plans for fused_rope_cache.rope_cache_update[_batched|_verify] — the
    rope + cache-write epilogue kernel (ops.fused_rope_cache). All three
    wrappers launch the same [B, T]-shaped kernel: solo is B=1, batched
    decode is T=1, spec-verify is the general B x T case."""
    T = int(shapes.get("T", 1))
    B = int(shapes.get("B", 1))
    L = int(shapes.get("L", 1))
    S = int(shapes["S"])
    n_kv = int(shapes["n_kv_heads"])
    hd = int(shapes["head_size"])
    cache_dtype = str(shapes.get("cache_dtype", "bfloat16"))
    batched = B > 1 or bool(shapes.get("batched", False))
    if not batched:
        name = "rope_cache_update"
    elif T == 1:
        name = "rope_cache_update_batched"
    else:
        name = "rope_cache_update_verify"
    kv_shape = (B, T, n_kv, hd)
    ops = (
        OperandPlan("k", kv_shape, (1,) + kv_shape[1:], "bfloat16"),
        OperandPlan("v", kv_shape, (1,) + kv_shape[1:], "bfloat16"),
        OperandPlan("cos", kv_shape[:2] + (1, hd // 2),
                    (1,) + kv_shape[1:2] + (1, hd // 2)),
        OperandPlan("sin", kv_shape[:2] + (1, hd // 2),
                    (1,) + kv_shape[1:2] + (1, hd // 2)),
        OperandPlan("k_cache[ANY]", (L, B, S, n_kv, hd), (L, B, S, n_kv, hd),
                    cache_dtype),
        OperandPlan("v_cache[ANY]", (L, B, S, n_kv, hd), (L, B, S, n_kv, hd),
                    cache_dtype),
        OperandPlan("k_scratch", kv_shape[1:], kv_shape[1:], cache_dtype),
        OperandPlan("v_scratch", kv_shape[1:], kv_shape[1:], cache_dtype),
    )
    return [KernelPlan(kernel=name, grid=(B,), operands=ops,
                       note=f"S={S} T={T} cache={cache_dtype}")]


def lowering_plan(kind: str, shapes: dict) -> list[KernelPlan]:
    """Enumerate every pallas_call (grid + BlockSpec blocks) the named
    kernel entry point would launch for the given logical shapes.

    ``kind``: "q40" | "q80" (shapes: T, K, O, optional L for the stacked
    scalar-prefetch variant, nosub, fused_norm, k_padded override),
    "flash_decode" (shapes: T, B, L, S, n_heads, n_kv_heads, head_size,
    cache_dtype), or "rope_cache" (shapes: T, B, L, S, n_kv_heads,
    head_size, cache_dtype).
    """
    if kind in ("q40", "q80"):
        return _quant_plans(kind, shapes)
    if kind == "flash_decode":
        return _flash_plans(shapes)
    if kind == "rope_cache":
        return _rope_cache_plans(shapes)
    raise ValueError(f"unknown kernel kind {kind!r}")


def verify(plans: list[KernelPlan]) -> list[str]:
    """All tiling violations across the plans (empty == lowerable)."""
    return [v for p in plans for v in p.violations()]


def check(kind: str, shapes: dict) -> list[KernelPlan]:
    """lowering_plan + verify; raises TilingError naming the offending
    kernel and block/array shapes on any violation."""
    plans = lowering_plan(kind, shapes)
    bad = verify(plans)
    if bad:
        raise TilingError(
            f"{kind} {shapes}: " + "; ".join(bad))
    return plans


# ---------------------------------------------------------------------------
# The CPU-sweepable shape matrix (the CI gate + the --json report)
# ---------------------------------------------------------------------------

#: real model dims the bench/CLI loads: (name, dim, hidden, n_heads,
#: n_kv_heads, head_size, vocab)
MODEL_DIMS = (
    ("llama2_7b", 4096, 11008, 32, 32, 128, 32000),
    ("llama3_8b", 4096, 14336, 32, 8, 128, 128256),
    ("tinyllama", 2048, 5632, 32, 4, 64, 32000),
    ("moe_mixtral", 4096, 14336, 32, 8, 128, 32000),
)

SWEEP_T = (1, 8, 64)


def sweep(ts=SWEEP_T, kinds=("q40", "q80"),
          cache_dtypes=("bfloat16", "float32", "float8_e4m3fn")) -> dict:
    """Run the full shape matrix; returns {case_name: [plan dicts]} with
    violations inline (the CI artifact). Raises nothing — callers gate on
    the 'violations' fields."""
    import math

    from dllama_tpu.ops.qmatmul import K_MULTIPLE, _pad_up
    from dllama_tpu.parallel.quant_tp import ROW_SHARD_GRANULARITY

    out = {}
    for name, dim, hidden, n_heads, n_kv, hd, vocab in MODEL_DIMS:
        L = 32
        for kind in kinds:
            for T in ts:
                for tag, K, O in (("qkv", dim, dim),
                                  ("kv_proj", dim, n_kv * hd),
                                  ("up", dim, hidden),
                                  ("down", hidden, dim),
                                  ("wcls", dim, vocab)):
                    for stacked in (None, L):
                        for fused in (False, True):
                            case = (f"{name}/{kind}/{tag}/T{T}"
                                    f"{'/stacked' if stacked else ''}"
                                    f"{'/fused_norm' if fused else ''}")
                            plans = lowering_plan(kind, dict(
                                T=T, K=K, O=O, L=stacked, fused_norm=fused))
                            out[case] = [p.to_dict() for p in plans]
                # row-parallel (--tp-reduce) repack: wo/w2 K-sharded per
                # device, each shard's K padded to K_MULTIPLE on its own —
                # the local kernel must keep a Mosaic-valid tiling at the
                # CHUNK width, not the full K (quant_tp.row_shard_quant_leaf)
                for tp in (2, 8):
                    fpw = _pad_up(hidden,
                                  math.lcm(K_MULTIPLE[kind], 128 * tp))
                    for tag, chunk, O in (("row_wo", dim // tp, dim),
                                          ("row_w2", fpw // tp, dim)):
                        if chunk % ROW_SHARD_GRANULARITY[kind]:
                            continue  # validate_tp_reduce declines these
                        for T in ts:
                            case = f"{name}/{kind}/{tag}/tp{tp}/T{T}/stacked"
                            plans = lowering_plan(kind, dict(
                                T=T, K=chunk, O=O, L=L,
                                k_padded=_pad_up(chunk, K_MULTIPLE[kind])))
                            out[case] = [p.to_dict() for p in plans]
        for dt in cache_dtypes:
            for T in (1, 8):
                case = f"{name}/flash/T{T}/{dt}"
                out[case] = [p.to_dict() for p in lowering_plan(
                    "flash_decode", dict(
                        T=T, L=L, S=2048, n_heads=n_heads,
                        n_kv_heads=n_kv, head_size=hd, cache_dtype=dt))]
            # solo decode (B=1, T up to spec-verify rows), batched decode
            # (T=1), and the batched spec-verify step (B x draft_len+1)
            for B, T in ((1, 1), (1, 9), (8, 1), (8, 9)):
                case = f"{name}/rope_cache/B{B}/T{T}/{dt}"
                out[case] = [p.to_dict() for p in lowering_plan(
                    "rope_cache", dict(
                        T=T, B=B, L=L, S=2048, n_kv_heads=n_kv,
                        head_size=hd, cache_dtype=dt, batched=B > 1))]
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Static TPU tiling verifier: sweep the kernel inventory")
    ap.add_argument("--json", action="store_true",
                    help="dump the full shape-matrix report as JSON")
    args = ap.parse_args(argv)
    report = sweep()
    n_viol = sum(len(p["violations"]) for plans in report.values()
                 for p in plans)
    if args.json:
        print(json.dumps({"cases": report, "n_cases": len(report),
                          "n_violations": n_viol}, indent=1))
    else:
        for case, plans in sorted(report.items()):
            for p in plans:
                for v in p["violations"]:
                    print(f"VIOLATION {case}: {v}")
        print(f"{len(report)} cases, {n_viol} violations")
    return 1 if n_viol else 0


if __name__ == "__main__":
    raise SystemExit(main())
