"""Normalization ops.

RMSNorm semantics match the reference exactly
(`/root/reference/src/funcs.cpp:94-123`): ``inv = 1/sqrt(mean(x^2) + 1e-5)``,
``y = w * (inv * x)`` — note eps is added to the *mean*, and the reference
computes everything in f32. We keep the accumulation in f32 regardless of the
activation dtype so bf16 runs stay numerically anchored.
"""

from __future__ import annotations

import jax.numpy as jnp

RMS_EPS = 1e-5


def rmsnorm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = RMS_EPS) -> jnp.ndarray:
    """RMS-normalize the last axis. x: [..., dim], weight: [dim]."""
    xf = x.astype(jnp.float32)
    inv = jnp.reciprocal(jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps))
    return (weight.astype(jnp.float32) * (xf * inv)).astype(x.dtype)
