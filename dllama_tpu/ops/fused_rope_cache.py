"""Fused rope + KV-cache-write epilogue for the decode hot path.

The unfused decode step rotates K on the VPU (``ops.rope.apply_rope``), casts
it to the cache dtype, and dynamic-update-slices it into the stacked
[L, (B,) S, kv, hd] cache — three HBM touches (read K, write rotated K, the
DUS read-modify-write of the cache slab) for what is arithmetically a handful
of multiplies per element. This kernel does the whole epilogue in one pass
(the memory-bound-neighbor fusion of PAPERS.md "Efficient Operation Fusion",
arXiv 2502.17728): K and V stream into VMEM once, K rotates in-register in
f32, both cast to the cache dtype in VMEM scratch, and a single async copy
lands exactly T rows at (layer, b, pos..pos+T) in the HBM-resident cache —
the caches ride ``memory_space=ANY`` with input→output aliasing, so the rest
of the cache is never touched.

Bit-identity with the unfused composition (tests/test_fused_ops.py): the
rotation uses the exact f32 op order of ``apply_rope`` and the exact cast
chain of the unfused write (f32 → activation dtype → cache dtype), and the
write start clamps the way ``dynamic_update_slice`` clamps (solo: start in
[0, S-T]; batched: each row in [0, S-1]).

Opt in with DLLAMA_FUSE_ROPE_CACHE=1 (decode-only: T <= 16, same bound as
flash decode's spec-verify ceiling). Engaged by models.llama's stacked-cache
attention blocks — the quantized layer-scan and the flash index-scan routes,
i.e. solo, batched, paged, and spec-verify serving — via the single
``engages`` gate below.
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp

from dllama_tpu import compat
from dllama_tpu.ops.rope import HALF, INTERLEAVED


def fuse_enabled() -> bool:
    return os.environ.get("DLLAMA_FUSE_ROPE_CACHE", "0") == "1"


def supports(T: int, cache_dtype) -> bool:
    """Shapes/dtypes the kernel handles; anything else → unfused path.

    T covers decode (1) through spec-verify batches with margin; prefill
    stays unfused BY DESIGN (its [T, kv, hd] scratch would be VMEM-sized,
    and prefill is MXU-bound, not epilogue-bound)."""
    return (
        T <= 16
        and jnp.dtype(cache_dtype) in (jnp.dtype(jnp.bfloat16),
                                       jnp.dtype(jnp.float32),
                                       jnp.dtype(jnp.float8_e4m3fn))
    )


#: (T, dtype) combinations already warned about — the fallback must be
#: observable but not per-trace noisy (same contract as flash_decode).
_declined: set = set()


def engages(T: int, cache_dtype) -> bool:
    """THE single gate for whether the decode cache write runs this kernel —
    used by models.llama's solo and batched attention blocks so the fused
    and unfused paths can never silently drift apart."""
    if not fuse_enabled():
        return False
    if supports(T, cache_dtype):
        return True
    if T > 16:
        # prefill-sized T declining is the design — see supports(); warning
        # would misread as "fusion is off" on runs whose decode engages it
        return False
    key = (T, jnp.dtype(cache_dtype).name)
    if key not in _declined:
        _declined.add(key)
        print(f"dllama: DLLAMA_FUSE_ROPE_CACHE=1 but rope+cache fusion "
              f"declines T={T} cache={key[1]} (need a bf16/f32/f8 cache) — "
              f"unfused rope + cache write used",
              file=sys.stderr, flush=True)
    return False


def _kernel(idx_ref, k_ref, v_ref, cos_ref, sin_ref, kc_hbm, vc_hbm,
            ko_hbm, vo_hbm, k_scr, v_scr, k_sem, v_sem, *, style):
    """Grid (B,). idx_ref = [layer, start_0, ..., start_{B-1}] (starts
    pre-clamped by the launchers); k/v blocks are [1, T, kv, hd]; caches
    [L, B, S, kv, hd] in HBM, aliased input→output so untouched rows carry
    through. kc_hbm/vc_hbm are the aliased inputs — never read here."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del kc_hbm, vc_hbm
    b = pl.program_id(0)
    layer = idx_ref[0]
    start = idx_ref[1 + b]
    kf = k_ref[0].astype(jnp.float32)   # [T, kv, hd]
    c = cos_ref[0].astype(jnp.float32)  # [T, 1, hd//2]
    s = sin_ref[0].astype(jnp.float32)
    # exactly ops.rope.apply_rope's f32 op order, then the unfused write's
    # cast chain (f32 -> activation dtype -> cache dtype) — bit-identical
    if style == INTERLEAVED:
        x0 = kf[..., 0::2]
        x1 = kf[..., 1::2]
        rot = jnp.stack([x0 * c - x1 * s, x0 * s + x1 * c],
                        axis=-1).reshape(kf.shape)
    elif style == HALF:
        half = kf.shape[-1] // 2
        x0 = kf[..., :half]
        x1 = kf[..., half:]
        rot = jnp.concatenate([x0 * c - x1 * s, x0 * s + x1 * c], axis=-1)
    else:
        raise ValueError(f"unknown rope style {style!r}")
    k_scr[...] = rot.astype(k_ref.dtype).astype(k_scr.dtype)
    v_scr[...] = v_ref[0].astype(v_scr.dtype)
    T = k_scr.shape[0]
    # one copy of EXACTLY T rows: rows beyond start+T are never written, so
    # a clamped start near the end of the sequence overwrites the same rows
    # dynamic_update_slice would, nothing more
    k_cp = pltpu.make_async_copy(
        k_scr, ko_hbm.at[layer, b, pl.ds(start, T)], k_sem)
    v_cp = pltpu.make_async_copy(
        v_scr, vo_hbm.at[layer, b, pl.ds(start, T)], v_sem)
    k_cp.start()
    v_cp.start()
    k_cp.wait()
    v_cp.wait()


def _launch(kr, vr, cos, sin, k5, v5, starts, layer, style, interpret):
    """kr/vr [B, T, kv, hd], cos/sin [B, T, 1, hd//2], caches [L, B, S, kv,
    hd], starts [B] i32 pre-clamped write rows -> (k_cache, v_cache)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, n_kv, hd = kr.shape
    idx = jnp.concatenate(
        [jnp.asarray(layer, jnp.int32).reshape(1), starts.astype(jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, T, n_kv, hd), lambda b, idx: (b, 0, 0, 0)),
            pl.BlockSpec((1, T, n_kv, hd), lambda b, idx: (b, 0, 0, 0)),
            pl.BlockSpec((1, T, 1, hd // 2), lambda b, idx: (b, 0, 0, 0)),  # dllama: allow[PALLAS-001] reason=whole-array dims (proven: tests/test_lowering.py sweep)
            pl.BlockSpec((1, T, 1, hd // 2), lambda b, idx: (b, 0, 0, 0)),  # dllama: allow[PALLAS-001] reason=whole-array dims (proven: tests/test_lowering.py sweep)
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((T, n_kv, hd), k5.dtype),
            pltpu.VMEM((T, n_kv, hd), v5.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, style=style),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k5.shape, k5.dtype),
            jax.ShapeDtypeStruct(v5.shape, v5.dtype),
        ],
        # operand index counts the scalar-prefetch idx (=0): k_cache is
        # operand 5, v_cache 6, aliased onto outputs 0/1 — the cache is
        # updated in place, untouched rows carried through
        input_output_aliases={5: 0, 6: 1},
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(idx, kr, vr, cos, sin, k5, v5)


@functools.partial(jax.jit, static_argnames=("style", "interpret"))
def rope_cache_update(
    k: jnp.ndarray,        # [T, n_kv, hd] — UNrotated K projection
    v: jnp.ndarray,        # [T, n_kv, hd]
    cos: jnp.ndarray,      # [T, 1, hd//2] — table rows pos..pos+T
    sin: jnp.ndarray,      # same
    k_cache: jnp.ndarray,  # [L, S, n_kv, hd]
    v_cache: jnp.ndarray,  # same
    pos: jnp.ndarray,      # scalar int32
    layer: jnp.ndarray,    # scalar int32
    style: str = INTERLEAVED,
    interpret: bool | None = None,
) -> tuple:
    """Solo decode: rotate K and land K/V at (layer, pos..pos+T) in one
    kernel. Returns the updated (k_cache, v_cache); bit-identical to
    ``apply_rope`` + ``dynamic_update_slice`` (incl. its end-clamp)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    T = k.shape[0]
    L, S, n_kv, hd = k_cache.shape
    start = jnp.clip(jnp.asarray(pos, jnp.int32), 0, S - T).reshape(1)
    kc, vc = _launch(
        k[None], v[None], cos.reshape(1, T, 1, hd // 2),
        sin.reshape(1, T, 1, hd // 2), k_cache[:, None], v_cache[:, None],
        start, layer, style, interpret)
    return kc[:, 0], vc[:, 0]


@functools.partial(jax.jit, static_argnames=("style", "interpret"))
def rope_cache_update_verify(
    k: jnp.ndarray,        # [B, T, n_kv, hd] — UNrotated draft-row K
    v: jnp.ndarray,        # [B, T, n_kv, hd]
    cos: jnp.ndarray,      # [B, T, 1, hd//2] — per-row, per-draft angles
    sin: jnp.ndarray,      # same
    k_cache: jnp.ndarray,  # [L, B, S, n_kv, hd]
    v_cache: jnp.ndarray,  # same
    pos: jnp.ndarray,      # [B] int32 — row b's base position
    layer: jnp.ndarray,    # scalar int32
    style: str = INTERLEAVED,
    interpret: bool | None = None,
) -> tuple:
    """Spec-verify decode: B rows x T draft tokens each, row b landing at
    (layer, b, pos[b]..pos[b]+T). The general [B, T] case of the two
    wrappers above (solo is B=1, batched is T=1); per-row starts clamp to
    [0, S-T] exactly like the vmapped ``dynamic_update_slice``."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, T = k.shape[:2]
    S = k_cache.shape[2]
    starts = jnp.clip(jnp.asarray(pos, jnp.int32), 0, S - T)
    return _launch(k, v, cos, sin, k_cache, v_cache, starts, layer, style,
                   interpret)


@functools.partial(jax.jit, static_argnames=("style", "interpret"))
def rope_cache_update_batched(
    k: jnp.ndarray,        # [B, n_kv, hd] — one UNrotated token per sequence
    v: jnp.ndarray,        # [B, n_kv, hd]
    cos: jnp.ndarray,      # [B, 1, hd//2] — each row's own angle
    sin: jnp.ndarray,      # same
    k_cache: jnp.ndarray,  # [L, B, S, n_kv, hd]
    v_cache: jnp.ndarray,  # same
    pos: jnp.ndarray,      # [B] int32 — each row's position
    layer: jnp.ndarray,    # scalar int32
    style: str = INTERLEAVED,
    interpret: bool | None = None,
) -> tuple:
    """Batched decode: B independent rows, row b landing at (layer, b,
    pos[b]). Clamps each row to the last slot exactly like the unfused
    scatter/DUS path, so overrun rows leave identical cache contents."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, n_kv, hd = k.shape
    L, Bc, S, _, _ = k_cache.shape
    assert B == Bc, (B, Bc)
    starts = jnp.clip(jnp.asarray(pos, jnp.int32), 0, S - 1)
    return _launch(
        k[:, None], v[:, None], cos[:, None], sin[:, None],
        k_cache, v_cache, starts, layer, style, interpret)
