"""Deterministic autoscaler policy: SLO pressure in, ScaleDecision out.

This module is the *brain* of the elastic fleet and deliberately knows
nothing about processes, sockets or threads: :func:`decide` is a pure
function from a window of :class:`Signals` observations (federated
burn-rate alert state + queue-depth / slot-occupancy / KV-pressure, the
sensors PR 15 built) to a :class:`ScaleDecision`. The fleet supervisor
(serving/fleet.py) owns the actuators — spawn, pre-warm, drain, SIGKILL
escalation — and simply executes whatever this module decides.

Keeping the policy pure buys the property the ISSUE demands: *alert flap
never becomes replica flap*, and that claim is checkable with a table of
synthetic histories (tests/test_autoscale.py) instead of a fleet of real
processes. Three mechanisms enforce it, all deterministic:

* **hysteresis band** — scale-up needs pressure >= ``up_pressure`` (or a
  firing burn-rate alert), scale-down needs pressure <= ``down_pressure``
  AND zero firing alerts; the band between them always holds.
* **consecutive-observation streaks** — one hot sample never scales up:
  the last ``up_consecutive`` observations must *all* be hot (and all of
  the last ``down_consecutive`` cold for scale-down), so a single flapping
  alert evaluation is absorbed by the window.
* **cooldowns + clamps** — after any scale attempt the policy holds for
  ``cooldown_up_s``/``cooldown_down_s`` (whichever direction it would move
  next), and the target replica count is always clamped to
  ``[min_replicas, max_replicas]``.

Pressure is the *max* of the normalized bottleneck resources (slot
occupancy, queue backlog relative to slots, KV-page consumption): scaling
has to respond to whichever resource saturates first, and a weighted
blend would let a saturated lane hide behind two idle ones.

Stdlib-only and jax-free, like the rest of the serving control plane.
"""

from __future__ import annotations

import threading
from collections import deque

from dllama_tpu.analysis.sanitize import guarded_by

#: decision actions
UP, DOWN, HOLD = "up", "down", "hold"


class Signals:
    """One autoscaler observation: the fleet-aggregate sensor sample the
    supervisor gathers each evaluation tick (from ``federate_alerts()``
    and the router's readiness aggregation)."""

    __slots__ = ("firing", "queue_depth", "slots_occupied", "slots_total",
                 "kv_pages_free", "kv_pages_total", "kv_pages_reclaimable")

    def __init__(self, firing: int = 0, queue_depth: int = 0,
                 slots_occupied: int = 0, slots_total: int = 0,
                 kv_pages_free: int = 0, kv_pages_total: int = 0,
                 kv_pages_reclaimable: int = 0):
        self.firing = int(firing)
        self.queue_depth = int(queue_depth)
        self.slots_occupied = int(slots_occupied)
        self.slots_total = int(slots_total)
        self.kv_pages_free = int(kv_pages_free)
        self.kv_pages_total = int(kv_pages_total)
        self.kv_pages_reclaimable = int(kv_pages_reclaimable)

    def pressure(self) -> float:
        """Normalized load in [0, 1]: the max over slot occupancy, queue
        backlog (relative to total slots, capped at 1) and KV-page
        consumption — the bottleneck resource, not an average.

        KV availability counts reclaimable pages: the radix cache
        deliberately retains finished rows' pages until an allocation
        needs them, so on an idle steady-state fleet ``kv_pages_free``
        sits near zero forever. Scoring only truly-free pages would pin
        pressure above every down threshold and starve scale-down — the
        cache-is-not-pressure distinction is what lets the fleet shed a
        replica in a trough."""
        occ = (self.slots_occupied / self.slots_total
               if self.slots_total > 0 else 0.0)
        queue = (min(1.0, self.queue_depth / self.slots_total)
                 if self.slots_total > 0
                 else (1.0 if self.queue_depth > 0 else 0.0))
        avail = self.kv_pages_free + self.kv_pages_reclaimable
        kv = (1.0 - avail / self.kv_pages_total
              if self.kv_pages_total > 0 else 0.0)
        return max(0.0, min(1.0, max(occ, queue, kv)))

    def __repr__(self) -> str:  # policy-table test failure readability
        return (f"Signals(firing={self.firing}, queue={self.queue_depth}, "
                f"occ={self.slots_occupied}/{self.slots_total}, "
                f"kv_free={self.kv_pages_free}"
                f"+{self.kv_pages_reclaimable}r/{self.kv_pages_total})")


class PolicyConfig:
    """Autoscaler knobs. Validated once at construction so a bad flag is
    a startup error, not a silent always-hold policy."""

    __slots__ = ("min_replicas", "max_replicas", "up_pressure",
                 "down_pressure", "up_consecutive", "down_consecutive",
                 "cooldown_up_s", "cooldown_down_s", "alert_up", "window")

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_pressure: float = 0.75, down_pressure: float = 0.25,
                 up_consecutive: int = 2, down_consecutive: int = 3,
                 cooldown_up_s: float = 5.0, cooldown_down_s: float = 20.0,
                 alert_up: int = 1, window: int = 0):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(f"max_replicas {max_replicas} < "
                             f"min_replicas {min_replicas}")
        if not (0.0 <= down_pressure < up_pressure <= 1.0):
            raise ValueError(
                f"need 0 <= down_pressure < up_pressure <= 1, got "
                f"down={down_pressure} up={up_pressure}")
        if up_consecutive < 1 or down_consecutive < 1:
            raise ValueError("consecutive streaks must be >= 1")
        if cooldown_up_s < 0 or cooldown_down_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if alert_up < 1:
            raise ValueError(f"alert_up must be >= 1, got {alert_up}")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_pressure = up_pressure
        self.down_pressure = down_pressure
        self.up_consecutive = up_consecutive
        self.down_consecutive = down_consecutive
        self.cooldown_up_s = cooldown_up_s
        self.cooldown_down_s = cooldown_down_s
        self.alert_up = alert_up
        # the window must be able to hold the longest streak it judges
        floor = max(up_consecutive, down_consecutive)
        self.window = max(int(window) or floor, floor)


class ScaleDecision:
    """What the policy wants done, and why (the reason strings are part
    of the test contract — the policy tables assert on them)."""

    __slots__ = ("action", "target", "reason", "pressure")

    def __init__(self, action: str, target: int, reason: str,
                 pressure: float):
        self.action = action    # "up" | "down" | "hold"
        self.target = target    # desired replica count, already clamped
        self.reason = reason
        self.pressure = pressure  # latest observation's pressure

    def __repr__(self) -> str:
        return (f"ScaleDecision({self.action}, target={self.target}, "
                f"reason={self.reason!r}, pressure={self.pressure:.2f})")


def _hot(sig: Signals, cfg: PolicyConfig) -> bool:
    """Scale-up evidence: saturated resources OR a firing burn-rate alert
    (the SLO is burning even if raw occupancy looks fine)."""
    return sig.pressure() >= cfg.up_pressure or sig.firing >= cfg.alert_up


def _cold(sig: Signals, cfg: PolicyConfig) -> bool:
    """Scale-down evidence: low pressure AND a completely quiet alert
    feed — we never shed capacity while any SLO window is burning."""
    return sig.pressure() <= cfg.down_pressure and sig.firing == 0


def decide(window, n_replicas: int, cfg: PolicyConfig = None,
           now: float = 0.0, last_scale_at: float = None) -> ScaleDecision:
    """The pure policy function.

    ``window`` is the observation history, oldest first (any sequence of
    :class:`Signals`); ``n_replicas`` the current count of replicas the
    fleet is paying for; ``last_scale_at`` the timestamp (same clock as
    ``now``) of the most recent scale *attempt* in either direction, or
    None if the fleet has never scaled. Deterministic: same arguments,
    same decision — there is no hidden clock or randomness to flake on.
    """
    cfg = cfg or PolicyConfig()
    latest_p = window[-1].pressure() if len(window) else 0.0

    def hold(reason: str) -> ScaleDecision:
        target = max(cfg.min_replicas, min(cfg.max_replicas, n_replicas))
        return ScaleDecision(HOLD, target, reason, latest_p)

    # clamp violations outrank everything: a fleet below min is underwater
    # no matter how quiet the sensors look (and above max, vice versa)
    if n_replicas < cfg.min_replicas:
        return ScaleDecision(UP, n_replicas + 1, "below_min", latest_p)
    if n_replicas > cfg.max_replicas:
        return ScaleDecision(DOWN, n_replicas - 1, "above_max", latest_p)

    if len(window) < min(cfg.up_consecutive, cfg.down_consecutive):
        return hold("warming")

    up_tail = list(window)[-cfg.up_consecutive:]
    up_eligible = (len(window) >= cfg.up_consecutive
                   and all(_hot(s, cfg) for s in up_tail))
    down_tail = list(window)[-cfg.down_consecutive:]
    down_eligible = (len(window) >= cfg.down_consecutive
                     and all(_cold(s, cfg) for s in down_tail))

    if up_eligible:
        if n_replicas >= cfg.max_replicas:
            return hold("at_max")
        if (last_scale_at is not None
                and now - last_scale_at < cfg.cooldown_up_s):
            return hold("cooldown_up")
        reason = ("alerts_firing"
                  if all(s.firing >= cfg.alert_up for s in up_tail)
                  else "pressure_high")
        return ScaleDecision(UP, n_replicas + 1, reason, latest_p)

    if down_eligible:
        if n_replicas <= cfg.min_replicas:
            return hold("at_min")
        if (last_scale_at is not None
                and now - last_scale_at < cfg.cooldown_down_s):
            return hold("cooldown_down")
        return ScaleDecision(DOWN, n_replicas - 1, "pressure_low", latest_p)

    return hold("hysteresis")


@guarded_by("_lock", "_last_scale_at")
class AutoscalePolicy:
    """Thin stateful wrapper: owns the observation window and the
    last-scale timestamp, delegates every judgement to :func:`decide`.
    Thread-safe because the fleet supervisor's periodic tick and a
    drill/operator-forced transition may race on the cooldown clock."""

    def __init__(self, cfg: PolicyConfig = None):
        self.cfg = cfg or PolicyConfig()
        self._lock = threading.Lock()
        self._window = deque(maxlen=self.cfg.window)
        self._last_scale_at: float = None

    def evaluate(self, now: float, n_replicas: int,
                 signals: Signals) -> ScaleDecision:
        """Record one observation and decide. A non-hold decision arms
        the cooldown immediately — the *attempt* counts, even if the
        execution later fails, so a failing spawn can't be retried in a
        tight loop."""
        with self._lock:
            self._window.append(signals)
            d = decide(tuple(self._window), n_replicas, self.cfg, now,
                       self._last_scale_at)
            if d.action != HOLD:
                self._last_scale_at = now
            return d

    def note_scale(self, now: float) -> None:
        """Arm the cooldown for an out-of-band scale event (an operator
        or drill-forced transition must still suppress the policy's next
        move, or the two controllers fight)."""
        with self._lock:
            self._last_scale_at = now

    def window_snapshot(self) -> tuple:
        with self._lock:
            return tuple(self._window)
