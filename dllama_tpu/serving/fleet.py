"""Local fleet supervisor: N ``dllama-api`` replicas + the router, one
command — and, with ``--autoscale``, the closed loop that makes fleet
size a control variable.

``cli fleet`` spawns N ``cli serve`` subprocesses sharing one model
artifact on consecutive ports, supervises them (a crashed replica restarts
under a per-replica budget, with a capped + jittered exponential backoff
so a crash-looping replica can't thundering-herd the supervisor; the
router's probe loop routes around it in the meantime), fronts them with
the in-process router, and on SIGTERM drains the whole topology in order:
stop restarting, SIGTERM every replica (each drains itself — finishes
in-flight work while its /ready flips 503 and the router stops sending
traffic), then stop the router.

The elastic loop (:class:`ElasticSupervisor`) closes sensors to
actuators: each tick it gathers the federated burn-rate alert state and
the fleet load aggregate, asks the pure policy engine
(:mod:`dllama_tpu.serving.autoscale`) for a :class:`ScaleDecision`, and
executes it live. Scale-up spawns a replica, registers it with the
router as ``joining``, pre-warms the fleet's hot prompt prefixes into it
over the existing ``kv_transfer`` page-stream (sibling ``/v1/prefill``
-> new replica ``/v1/kv/import``) and only then activates it for
traffic; a pre-warm failure (source dies mid-transfer) degrades to a
cold join, counted. Scale-down picks the least-loaded replica, marks it
``draining`` (no new picks, never a resume target), SIGTERMs it so it
finishes its in-flight streams itself, and escalates to SIGKILL at the
drain deadline — at which point the router's CheckpointStore +
``/v1/kv/resume`` machinery migrates any still-open stream to a sibling
byte-identically. Every transition is a ``policy_eval`` / ``scale_up`` /
``scale_down`` fault seam and a row on
``dllama_fleet_scale_events_total``.

This is the test/bench topology — real deployments run ``cli serve`` per
machine under an orchestrator and ``cli router`` in front — but it is the
SAME code path: the router cannot tell fleet-spawned replicas from remote
ones, which is exactly what makes the fleet e2e tests meaningful.

Stdlib-only and jax-free: the replicas import jax in their own processes;
the supervisor is pure process + socket plumbing.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time

from dllama_tpu import faults, observability
from dllama_tpu.analysis.sanitize import guarded_by
from dllama_tpu.serving import autoscale
from dllama_tpu.serving import kv_transfer
from dllama_tpu.serving import router as router_mod


def restart_backoff_s(restarts: int, base_s: float = 0.5,
                      cap_s: float = 8.0, jitter_frac: float = 0.25,
                      salt: int = 0) -> float:
    """Crash-restart delay before restart number ``restarts + 1``.

    The first restart is immediate (a one-off crash should heal at once);
    after that the delay doubles from ``base_s`` and is CAPPED at
    ``cap_s`` — an uncapped exponential turns a persistently-failing
    replica into an effectively-retired one, hiding the crash loop.
    Deterministic jitter (hashed from ``salt``, normally the replica's
    port, and the restart count — never a PRNG, so drills replay exactly)
    spreads up to ``jitter_frac`` of the delay on top, so N replicas all
    killed by one cause don't restart in lockstep and reload weights
    against the same disk at the same instant."""
    if restarts <= 0:
        return 0.0
    delay = min(cap_s, base_s * (2 ** (restarts - 1)))
    spread = ((salt * 2654435761 + restarts * 40503) % 1024) / 1024.0
    return delay * (1.0 + jitter_frac * spread)


class ReplicaProc:
    """Bookkeeping for one replica subprocess (mutated only by Fleet under
    Fleet's lock)."""

    def __init__(self, index: int, host: str, port: int, argv: list):
        self.index = index
        self.host = host
        self.port = port
        self.argv = argv
        self.proc: subprocess.Popen = None
        self.restarts = 0
        self.next_restart_at = None  # backoff deadline; None = no crash seen
        self.retiring = False  # scale-down in progress: exits are expected
        self.env: dict = None  # per-replica overrides (trace part file)

    @property
    def name(self) -> str:
        return f"{self.host}:{self.port}"


@guarded_by("_lock", "_draining", "_stopped", "replicas")
class Fleet:
    """Spawn + supervise + drain replica subprocesses.

    The replica tuple is rebound only under ``_lock`` (the elastic
    supervisor adds and removes replicas live); readers snapshot
    ``self.replicas`` once and iterate that. Each ReplicaProc's
    ``proc``/``restarts``/``retiring`` fields are only touched by
    :meth:`_spawn`/:meth:`poll_restart`/:meth:`drain` and the scale
    transitions, all serialized by ``_lock`` — the supervision thread,
    the elastic supervisor and the signal-initiated drain thread race on
    exactly those."""

    def __init__(self, model: str, tokenizer: str, n_replicas: int = 2,
                 base_port: int = 9990, host: str = "127.0.0.1",
                 replica_args: list = (), max_restarts: int = 3,
                 log_dir: str = None, env: dict = None,
                 roles: list = None,
                 restart_backoff_base_s: float = 0.5,
                 restart_backoff_cap_s: float = 8.0):
        self.model = model
        self.tokenizer = tokenizer
        self.host = host
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.env = dict(env if env is not None else os.environ)
        self.replica_args = list(replica_args)
        self.restart_backoff_base_s = restart_backoff_base_s
        self.restart_backoff_cap_s = restart_backoff_cap_s
        self._lock = threading.Lock()
        self._draining = False
        self._stopped = threading.Event()
        self._supervision: threading.Thread = None
        # scaled-up replicas take fresh ports/indices after the static set
        self._next_port = base_port + n_replicas
        self._next_index = n_replicas
        # per-replica disaggregation role ("prefill"/"decode"/"both"),
        # aligned by index; a role rides the replica's argv so a restart
        # comes back with the same role it crashed with
        roles = list(roles or [])
        self.replicas = tuple(
            ReplicaProc(i, host, base_port + i, self._replica_argv(
                base_port + i,
                roles[i] if i < len(roles) else "both"))
            for i in range(n_replicas))
        # each replica writes its own trace PART file next to the
        # supervisor's: N processes appending to one file would interleave
        # mid-line; run_fleet stitches the parts (skew-corrected) at drain
        if self.env.get("DLLAMA_TRACE"):
            for r in self.replicas:
                r.env = dict(self.env, DLLAMA_TRACE=self.trace_part(r))

    def _replica_argv(self, port: int, role: str = "both") -> list:
        return ([sys.executable, "-m", "dllama_tpu.cli", "serve",
                 "--model", self.model, "--tokenizer", self.tokenizer,
                 "--host", self.host, "--port", str(port)]
                + (["--role", role] if role and role != "both" else [])
                + list(self.replica_args))

    def trace_part(self, r: ReplicaProc):
        """The per-replica trace part file path (None: tracing off)."""
        base = self.env.get("DLLAMA_TRACE")
        return f"{base}.replica-{r.port}" if base else None

    def addresses(self) -> list:
        return [r.name for r in self.replicas]

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def _open_log(self, r: ReplicaProc):
        if not self.log_dir:
            return None  # inherit the supervisor's stderr
        os.makedirs(self.log_dir, exist_ok=True)
        return open(os.path.join(self.log_dir,
                                 f"replica-{r.index}.log"), "ab")

    def _spawn(self, r: ReplicaProc) -> None:
        """Start (or restart) one replica. Caller holds ``_lock``."""
        log = self._open_log(r)
        r.proc = subprocess.Popen(
            r.argv, env=r.env if r.env is not None else self.env,
            stdout=log, stderr=subprocess.STDOUT if log else None,
            start_new_session=True)  # own process group: a ^C at the
        #   supervisor's terminal must not SIGINT replicas mid-drain
        if log is not None:
            log.close()  # Popen holds its own fd

    def start(self) -> None:
        with self._lock:
            for r in self.replicas:
                self._spawn(r)

    # -- elastic scale transitions ---------------------------------------

    def add_replica(self, role: str = "both"):
        """Spawn one more replica on the next free port and add it to the
        supervised set. Returns its ReplicaProc, or None while draining
        (the shutdown path must never race a scale-up)."""
        with self._lock:
            if self._draining:
                return None
            port = self._next_port
            self._next_port += 1
            r = ReplicaProc(self._next_index, self.host, port,
                            self._replica_argv(port, role))
            self._next_index += 1
            if self.env.get("DLLAMA_TRACE"):
                r.env = dict(self.env, DLLAMA_TRACE=self.trace_part(r))
            self._spawn(r)
            self.replicas = self.replicas + (r,)
        return r

    def mark_retiring(self, r: ReplicaProc) -> None:
        """Flag a replica as intentionally going away: poll_restart stops
        resurrecting it (its exit is the drain completing, not a crash)."""
        with self._lock:
            r.retiring = True

    def remove_replica(self, r: ReplicaProc) -> None:
        with self._lock:
            self.replicas = tuple(x for x in self.replicas if x is not r)

    def drain_one(self, r: ReplicaProc, timeout_s: float = 30.0) -> bool:
        """SIGTERM one (already ``retiring``) replica and wait for its
        graceful exit; escalate to SIGKILL at the deadline. Returns True
        for a graceful drain — False means the process had to be killed
        (by us at the deadline, or by out-of-band chaos mid-drain) and
        any in-flight stream it held is now the router's resume problem."""
        p = r.proc
        if p is None:
            return True
        if p.poll() is None:
            p.terminate()
        try:
            p.wait(timeout=max(0.1, timeout_s))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            return False
        return p.returncode != -signal.SIGKILL

    def kill_replica(self, r: ReplicaProc) -> None:
        """Hard-stop a replica that never became ready (failed spawn)."""
        p = r.proc
        if p is not None and p.poll() is None:
            p.kill()
            p.wait()

    # -- readiness --------------------------------------------------------

    @staticmethod
    def _probe_ready(host: str, port: int, timeout_s: float = 1.0) -> bool:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
            try:
                conn.request("GET", "/ready")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False  # not up yet — the caller polls

    def wait_ready(self, timeout_s: float = 180.0) -> bool:
        """Block until EVERY replica answers /ready 200 (model loaded,
        scheduler up). A replica process that already exited fails fast —
        waiting out the full timeout on a crashed replica helps nobody."""
        deadline = time.monotonic() + timeout_s
        pending = list(self.replicas)
        while pending and time.monotonic() < deadline:
            still = []
            for r in pending:
                if r.proc is not None and r.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {r.name} exited with "
                        f"{r.proc.returncode} before becoming ready")
                if not self._probe_ready(r.host, r.port):
                    still.append(r)
            pending = still
            if pending:
                time.sleep(0.25)
        return not pending

    def wait_ready_one(self, r: ReplicaProc,
                       timeout_s: float = 180.0) -> bool:
        """Like :meth:`wait_ready` for a single (scaled-up) replica, but
        a pre-ready exit returns False instead of raising — a failed
        spawn is a counted scale-up outcome, not a fleet-fatal error."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if r.proc is not None and r.proc.poll() is not None:
                return False
            if self._probe_ready(r.host, r.port):
                return True
            time.sleep(0.25)
        return False

    # -- crash supervision ------------------------------------------------

    def poll_restart(self) -> int:
        """One supervision pass: restart every exited replica still under
        its restart budget whose backoff window has elapsed. Returns the
        number restarted. The router keeps routing around the hole while
        the restart loads weights."""
        n = 0
        now = time.monotonic()
        with self._lock:
            if self._draining:
                return 0  # exits during drain are the POINT, not crashes
            for r in self.replicas:
                if r.retiring:
                    continue  # scale-down exits are the point too
                if r.proc is None or r.proc.poll() is None:
                    r.next_restart_at = None  # alive: clear any pending
                    continue
                if r.restarts >= self.max_restarts:
                    continue  # crash-looping: leave it down, the probe
                    #            loop keeps it out of rotation
                if r.next_restart_at is None:
                    # first pass to observe THIS exit: arm the backoff
                    r.next_restart_at = now + restart_backoff_s(
                        r.restarts, self.restart_backoff_base_s,
                        self.restart_backoff_cap_s, salt=r.port)
                if now < r.next_restart_at:
                    continue  # still backing off
                r.restarts += 1
                r.next_restart_at = None
                print(f"🔁 replica {r.name} exited "
                      f"({r.proc.returncode}); restart "
                      f"{r.restarts}/{self.max_restarts}", file=sys.stderr)
                self._spawn(r)
                n += 1
        return n

    def _supervision_loop(self, interval_s: float) -> None:
        while not self._stopped.is_set():
            self.poll_restart()
            self._stopped.wait(interval_s)

    def start_supervision(self, interval_s: float = 1.0) -> None:
        if self._supervision is not None:
            return
        self._supervision = threading.Thread(
            target=self._supervision_loop, args=(interval_s,),
            daemon=True, name="dllama-fleet-supervise")
        self._supervision.start()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """SIGTERM every replica (each runs its own graceful drain: /ready
        flips 503, in-flight requests finish) and wait; SIGKILL stragglers
        at the deadline. Returns True when every replica exited in time."""
        with self._lock:
            self._draining = True
        self._stopped.set()
        procs = [r.proc for r in self.replicas if r.proc is not None]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout_s
        clean = True
        for p in procs:
            left = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.1, left))
            except subprocess.TimeoutExpired:
                clean = False
                p.kill()
                p.wait()
        return clean


def _post_json(host: str, port: int, path: str, obj: dict,
               connect_timeout_s: float = 2.0,
               read_timeout_s: float = None) -> tuple:
    """One JSON POST: (status, content_type, body). Raises OSError-family
    on transport failure (the caller owns the degradation)."""
    body = json.dumps(obj).encode()
    conn = http.client.HTTPConnection(host, port,
                                      timeout=connect_timeout_s)
    try:
        conn.request("POST", path, body,
                     headers={"Content-Type": "application/json"})
        if conn.sock is not None:
            conn.sock.settimeout(read_timeout_s)
        resp = conn.getresponse()
        return resp.status, (resp.getheader("Content-Type") or ""), \
            resp.read()
    finally:
        conn.close()


def _post_kv(host: str, port: int, path: str, payload: bytes,
             connect_timeout_s: float = 2.0,
             read_timeout_s: float = None) -> tuple:
    """One framed-KV POST: (status, body)."""
    conn = http.client.HTTPConnection(host, port,
                                      timeout=connect_timeout_s)
    try:
        conn.request("POST", path, payload,
                     headers={"Content-Type": kv_transfer.CONTENT_TYPE})
        if conn.sock is not None:
            conn.sock.settimeout(read_timeout_s)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


@guarded_by("_lock", "_stopped")
class ElasticSupervisor:
    """The closed loop: sensors -> pure policy -> actuators.

    Each tick (:meth:`step`) gathers one :class:`autoscale.Signals`
    observation from the router's federated alert feed and fleet load
    aggregate, lets the policy decide, and executes. ``_lock`` serializes
    the scale transitions themselves — the periodic tick and an
    operator/drill-forced :meth:`scale_down` may race, and two concurrent
    transitions (or a transition racing the shutdown drain) must never
    interleave their spawn/drain/deregister sequences."""

    def __init__(self, fleet: Fleet, state, policy, interval_s: float = 1.0,
                 ready_timeout_s: float = 180.0,
                 drain_timeout_s: float = 30.0,
                 prewarm_prompts: int = 4, prewarm_tokens: int = 16):
        self.fleet = fleet
        self.state = state  # RouterState (same process — run_fleet wiring)
        self.policy = policy
        self.interval_s = interval_s
        self.ready_timeout_s = ready_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.prewarm_prompts = prewarm_prompts
        self.prewarm_tokens = prewarm_tokens
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._thread: threading.Thread = None

    # -- loop plumbing ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dllama-fleet-autoscale")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=self.drain_timeout_s + 5.0)

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the loop must live
                print(f"⚠️ autoscale tick failed: {e!r}", file=sys.stderr)
            self._stopped.wait(self.interval_s)

    # -- sensors ----------------------------------------------------------

    def signals(self) -> autoscale.Signals:
        """One observation: the federated burn-rate firing count plus the
        router's aggregate of every ACTIVE replica's load snapshot."""
        alerts = self.state.federate_alerts()
        _, info = self.state.readiness()
        agg = info.get("fleet") or {}
        return autoscale.Signals(
            firing=int(alerts.get("firing") or 0),
            queue_depth=agg.get("queue_depth", 0),
            slots_occupied=agg.get("slots_occupied", 0),
            slots_total=agg.get("slots_total", 0),
            kv_pages_free=agg.get("kv_pages_free", 0),
            kv_pages_total=agg.get("kv_pages_total", 0),
            kv_pages_reclaimable=agg.get("kv_pages_reclaimable", 0))

    def n_replicas(self) -> int:
        return len([r for r in self.fleet.replicas if not r.retiring])

    # -- the tick ---------------------------------------------------------

    def step(self):
        """One policy evaluation + execution. Fires the ``policy_eval``
        seam — an injected fault skips exactly one tick (counted as
        decision="injected"); the loop and the window survive."""
        if self.fleet.draining or self._stopped.is_set():
            return None
        try:
            faults.fire("policy_eval")
        except faults.FaultInjected:
            self.state._m_policy_evals.inc(decision="injected")
            return None
        decision = self.policy.evaluate(time.monotonic(),
                                        self.n_replicas(), self.signals())
        self.state._m_policy_evals.inc(decision=decision.action)
        if decision.action == autoscale.UP:
            self.scale_up()
        elif decision.action == autoscale.DOWN:
            self.scale_down()
        return decision

    # -- actuators --------------------------------------------------------

    def scale_up(self) -> bool:
        """Spawn -> register joining -> wait ready -> pre-warm ->
        activate. Every failure path is counted and leaves the fleet in
        the pre-attempt state (a spawned-but-never-ready process is
        killed and deregistered, not leaked)."""
        st = self.state
        with self._lock:
            try:
                faults.fire("scale_up")
            except faults.FaultInjected:
                st._m_scale_events.inc(event="injected")
                return False
            r = self.fleet.add_replica()
            if r is None:
                return False  # shutting down
            rep = st.register_replica(r.host, r.port)
            print(f"📈 scale-up: spawning replica {r.name}",
                  file=sys.stderr)
            if not self.fleet.wait_ready_one(r, self.ready_timeout_s):
                st._m_scale_events.inc(event="spawn_failed")
                print(f"📈 scale-up: replica {r.name} never became ready; "
                      f"rolling back", file=sys.stderr)
                self.fleet.kill_replica(r)
                self.fleet.remove_replica(r)
                st.deregister_replica(r.name)
                return False
            st.probe_replica(rep)  # a real load picture before traffic
            if not self._prewarm(r):
                st._m_scale_events.inc(event="prewarm_fallback")
                print(f"📈 scale-up: pre-warm failed; {r.name} joins cold",
                      file=sys.stderr)
            st.activate_replica(r.name)  # counts the "joined" event
            print(f"📈 scale-up: replica {r.name} active", file=sys.stderr)
            return True

    def _prewarm(self, r) -> bool:
        """Warm the new replica's radix cache with the fleet's hot prompt
        prefixes before it takes traffic: replay each recorded prompt
        through a warm sibling's ``/v1/prefill`` (nearly free there — the
        sibling's radix cache already holds the prefix pages) and relay
        the framed KV page-stream into the NEW replica's
        ``/v1/kv/import``, which publishes the prompt's pages into its
        radix tree. True = warm join (vacuously, when there is nothing to
        warm); False = cold join (source died mid-transfer or no sibling
        — the caller counts it, traffic starts cold, correctness is
        untouched)."""
        st = self.state
        prompts = st.hot_prompts.top(self.prewarm_prompts)
        if not prompts:
            return True
        try:
            sibling, _ = st.pick([], exclude=frozenset({r.name}))
        except (router_mod.NoReplicaAvailable, faults.FaultInjected):
            return False
        warmed = 0
        for body in prompts:
            req = dict(body, stream=False, kv_wire=st.kv_wire,
                       max_tokens=self.prewarm_tokens)
            req.pop("n", None)
            try:
                status, ctype, payload = _post_json(
                    sibling.host, sibling.port, "/v1/prefill", req,
                    connect_timeout_s=st.connect_timeout_s,
                    read_timeout_s=self.ready_timeout_s)
                if status != 200:
                    continue  # this prompt won't warm; try the others
                if kv_transfer.CONTENT_TYPE not in ctype:
                    continue  # finished inside the first chunk: no pages
                status, _ = _post_kv(
                    r.host, r.port, "/v1/kv/import", payload,
                    connect_timeout_s=st.connect_timeout_s,
                    read_timeout_s=self.ready_timeout_s)
                if status == 200:
                    warmed += 1
            except OSError:
                # the transfer tore mid-flight (source died, new replica
                # hiccuped): cold join, never a blocked scale-up
                return False
        return warmed > 0

    def scale_down(self, target: str = None) -> bool:
        """Retire one replica with zero client-visible errors: mark it
        ``draining`` router-side (no new picks, no resume targeting),
        SIGTERM it so it finishes its own in-flight streams, escalate to
        SIGKILL at the drain deadline (the router's checkpoint/resume
        machinery then migrates any still-open stream to a sibling), and
        deregister. ``target`` pins the victim by name (drills and
        operators); the policy path picks the least-loaded active
        replica."""
        st = self.state
        with self._lock:
            try:
                faults.fire("scale_down")
            except faults.FaultInjected:
                st._m_scale_events.inc(event="injected")
                return False
            procs = [p for p in self.fleet.replicas if not p.retiring]
            if target is None and len(procs) <= 1:
                return False  # never retire the last replica
            victim = None
            if target is not None:
                for p in procs:
                    if p.name == target:
                        victim = p
                        break
                if victim is None:
                    return False
            else:
                # least-loaded ACTIVE replica by the router's own scoring
                # (the same load_score that routes traffic ranks who has
                # the least to drain)
                scores = {}
                for rep in st.replicas:
                    s = rep.snapshot()
                    if s["state"] == router_mod.LIFECYCLE_ACTIVE:
                        scores[s["name"]] = router_mod.load_score(s)
                scored = [p for p in procs if p.name in scores]
                if not scored:
                    return False
                victim = min(scored, key=lambda p: scores[p.name])
            print(f"📉 scale-down: draining replica {victim.name}",
                  file=sys.stderr)
            self.fleet.mark_retiring(victim)
            st.drain_replica(victim.name)  # counts the "draining" event
            graceful = self.fleet.drain_one(victim, self.drain_timeout_s)
            if not graceful:
                # deadline escalation or out-of-band SIGKILL mid-drain:
                # the replica's in-flight streams are now failing over
                # through the checkpoint store — counted, not hidden
                st._m_scale_events.inc(event="drain_killed")
                print(f"📉 scale-down: {victim.name} needed SIGKILL; "
                      f"streams failing over", file=sys.stderr)
            self.fleet.remove_replica(victim)
            st.deregister_replica(victim.name)  # counts "retired"
            print(f"📉 scale-down: replica {victim.name} retired "
                  f"({'graceful' if graceful else 'killed'})",
                  file=sys.stderr)
            return True


def merge_fleet_trace(fleet: Fleet, state) -> int:
    """Stitch the per-replica trace part files into the supervisor's own
    (router) trace file, each shifted by the negated clock offset the
    probe loop estimated for that replica — this is what makes a replica's
    queue/prefill/decode spans nest under the router's proxy spans on one
    timeline despite monotonic-clock skew. Consumes the part files and
    returns the number of events merged; no-op when tracing is off."""
    base = observability.trace_path()
    if base is None:
        return 0
    offsets = {}
    if state is not None:
        offsets = {rep.name: rep.clock_offset_us()
                   for rep in state.replicas}
    parts = []
    for r in fleet.replicas:
        part = fleet.trace_part(r)
        if part and os.path.exists(part):
            # merge_trace_parts ADDS its delta to each ts: subtracting the
            # replica's offset moves its stamps onto the router's clock
            parts.append((part, -offsets.get(r.name, 0)))
    if not parts:
        return 0
    n = observability.merge_trace_parts(base, parts)
    for part, _ in parts:
        try:
            os.remove(part)
        except OSError:
            pass  # the events are already merged; a leftover part file
            #       is clutter, not a failure
    print(f"🧵 merged {n} replica trace event(s) from {len(parts)} part "
          f"file(s) into {base}", file=sys.stderr)
    return n


def supervisor_from_args(args, fleet: Fleet, state) -> ElasticSupervisor:
    """Build the elastic loop from ``cli fleet --autoscale`` flags."""
    cfg = autoscale.PolicyConfig(
        min_replicas=getattr(args, "min_replicas", 1) or 1,
        max_replicas=getattr(args, "max_replicas", 0) or args.replicas,
        up_pressure=getattr(args, "scale_up_pressure", 0.75),
        down_pressure=getattr(args, "scale_down_pressure", 0.25),
        cooldown_up_s=getattr(args, "scale_cooldown_up", 5.0),
        cooldown_down_s=getattr(args, "scale_cooldown_down", 20.0))
    return ElasticSupervisor(
        fleet, state, autoscale.AutoscalePolicy(cfg),
        interval_s=getattr(args, "scale_interval", 1.0),
        ready_timeout_s=args.ready_timeout,
        drain_timeout_s=args.drain_timeout,
        prewarm_tokens=getattr(args, "prewarm_tokens", 16))


def run_fleet(args) -> None:
    """``cli fleet``: the whole local topology — N replicas + router —
    supervised until SIGTERM/SIGINT, then drained in order."""
    replica_args = []
    for extra in getattr(args, "replica_arg", None) or []:
        replica_args.extend(extra.split())
    # the router's --ckpt-interval rides every replica's argv as the
    # serve-side default cadence, so fleet-wide checkpointing is one flag;
    # an explicit --replica-arg '--ckpt-interval ...' later in the argv
    # wins (argparse keeps the last occurrence)
    if "--ckpt-interval" not in replica_args:
        replica_args = (["--ckpt-interval",
                         str(getattr(args, "ckpt_interval", 32))]
                        + replica_args)
    # --slo-classes rides every replica's argv the same way: one fleet
    # flag configures every lane, --replica-arg still overrides
    slo_spec = getattr(args, "slo_classes", None)
    if slo_spec and "--slo-classes" not in replica_args:
        replica_args = ["--slo-classes", slo_spec] + replica_args
    # --ts-interval too: one flag sets the whole fleet's history cadence
    # (router + every replica sampler), --replica-arg still overrides
    if "--ts-interval" not in replica_args:
        replica_args = (["--ts-interval",
                         str(getattr(args, "ts_interval", 1.0))]
                        + replica_args)
    # --prefill N --decode M carve the first N+M replicas into dedicated
    # disaggregation roles (the rest stay "both"); the router migrates
    # only when it can see at least one routable replica of EACH
    n_pre = getattr(args, "prefill", 0) or 0
    n_dec = getattr(args, "decode", 0) or 0
    if bool(n_pre) != bool(n_dec):
        raise SystemExit("--prefill and --decode go together: migration "
                         "needs at least one replica of each role")
    if n_pre + n_dec > args.replicas:
        raise SystemExit(f"--prefill {n_pre} + --decode {n_dec} exceeds "
                         f"--replicas {args.replicas}")
    autoscaling = getattr(args, "autoscale", False)
    if autoscaling and n_pre:
        raise SystemExit("--autoscale and --prefill/--decode are mutually "
                         "exclusive: scaled replicas join as role 'both'")
    roles = (["prefill"] * n_pre + ["decode"] * n_dec
             + ["both"] * (args.replicas - n_pre - n_dec))
    fleet = Fleet(
        args.model, args.tokenizer,
        n_replicas=args.replicas, base_port=args.base_port,
        host=args.replica_host, replica_args=replica_args,
        max_restarts=args.max_restarts, log_dir=args.log_dir,
        roles=roles)
    print(f"🚀 spawning {args.replicas} replicas on "
          f"{args.replica_host}:{args.base_port}..."
          f"{args.base_port + args.replicas - 1}"
          + (f" ({n_pre} prefill + {n_dec} decode + "
             f"{args.replicas - n_pre - n_dec} both)" if n_pre else ""))
    fleet.start()
    state = None
    supervisor = None
    try:
        if not fleet.wait_ready(args.ready_timeout):
            raise RuntimeError(
                f"fleet not ready within {args.ready_timeout:.0f}s")
        fleet.start_supervision()
        state = router_mod.state_from_args(args, fleet.addresses())
        observability.emit_process_name("router")
        state.probe_once()
        state.start_probes()
        if autoscaling:
            supervisor = supervisor_from_args(args, fleet, state)
            supervisor.start()
            print(f"🪜 autoscale on: "
                  f"{supervisor.policy.cfg.min_replicas}..."
                  f"{supervisor.policy.cfg.max_replicas} replicas, "
                  f"eval every {supervisor.interval_s:g}s")
        srv = router_mod.create_router_server(
            state, host=args.host, port=args.port)

        def _drain(_signum=None, _frame=None):
            # off the signal frame: drain blocks up to --drain-timeout and
            # srv.shutdown blocks until serve_forever returns
            print(f"⛔ draining fleet (up to {args.drain_timeout:.0f}s) ...",
                  file=sys.stderr)

            def _run():
                if supervisor is not None:
                    supervisor.stop()
                fleet.drain(args.drain_timeout)
                state.stop_probes()
                srv.shutdown()

            threading.Thread(target=_run, daemon=True,
                             name="dllama-fleet-drain").start()

        try:
            signal.signal(signal.SIGTERM, _drain)
            signal.signal(signal.SIGINT, _drain)
        except ValueError:
            pass  # not the main thread (embedded/test use): no signal hook
        print(f"🛰️  fleet front door on {args.host}:{args.port} -> "
              f"{', '.join(fleet.addresses())}")
        srv.serve_forever()
    finally:
        # belt over braces: serve_forever exits via drain in the normal
        # path, but a startup failure must never orphan replica processes
        if supervisor is not None:
            supervisor.stop()
        fleet.drain(timeout_s=min(5.0, args.drain_timeout))
        # replicas are down (their trace files are final): stitch the
        # parts into the one merged fleet trace
        merge_fleet_trace(fleet, state)
