"""Local fleet supervisor: N ``dllama-api`` replicas + the router, one
command.

``cli fleet`` spawns N ``cli serve`` subprocesses sharing one model
artifact on consecutive ports, supervises them (a crashed replica restarts
under a per-replica budget; the router's probe loop routes around it in
the meantime), fronts them with the in-process router, and on SIGTERM
drains the whole topology in order: stop restarting, SIGTERM every replica
(each drains itself — finishes in-flight work while its /ready flips 503
and the router stops sending traffic), then stop the router.

This is the test/bench topology — real deployments run ``cli serve`` per
machine under an orchestrator and ``cli router`` in front — but it is the
SAME code path: the router cannot tell fleet-spawned replicas from remote
ones, which is exactly what makes the fleet e2e tests meaningful.

Stdlib-only and jax-free: the replicas import jax in their own processes;
the supervisor is pure process + socket plumbing.
"""

from __future__ import annotations

import http.client
import os
import signal
import subprocess
import sys
import threading
import time

from dllama_tpu import observability
from dllama_tpu.analysis.sanitize import guarded_by
from dllama_tpu.serving import router as router_mod


class ReplicaProc:
    """Bookkeeping for one replica subprocess (mutated only by Fleet under
    Fleet's lock)."""

    def __init__(self, index: int, host: str, port: int, argv: list):
        self.index = index
        self.host = host
        self.port = port
        self.argv = argv
        self.proc: subprocess.Popen = None
        self.restarts = 0
        self.env: dict = None  # per-replica overrides (trace part file)

    @property
    def name(self) -> str:
        return f"{self.host}:{self.port}"


@guarded_by("_lock", "_draining", "_stopped")
class Fleet:
    """Spawn + supervise + drain N replica subprocesses.

    The replica list itself is immutable after construction; each
    ReplicaProc's ``proc``/``restarts`` fields are only touched by
    :meth:`_spawn`/:meth:`poll_restart`/:meth:`drain`, all serialized by
    ``_lock`` — the supervision thread and the signal-initiated drain
    thread race on exactly those."""

    def __init__(self, model: str, tokenizer: str, n_replicas: int = 2,
                 base_port: int = 9990, host: str = "127.0.0.1",
                 replica_args: list = (), max_restarts: int = 3,
                 log_dir: str = None, env: dict = None,
                 roles: list = None):
        self.host = host
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.env = dict(env if env is not None else os.environ)
        self._lock = threading.Lock()
        self._draining = False
        self._stopped = threading.Event()
        self._supervision: threading.Thread = None
        # per-replica disaggregation role ("prefill"/"decode"/"both"),
        # aligned by index; a role rides the replica's argv so a restart
        # comes back with the same role it crashed with
        roles = list(roles or [])
        self.replicas = tuple(
            ReplicaProc(i, host, base_port + i, [
                sys.executable, "-m", "dllama_tpu.cli", "serve",
                "--model", model, "--tokenizer", tokenizer,
                "--host", host, "--port", str(base_port + i),
            ] + (["--role", roles[i]]
                 if i < len(roles) and roles[i] != "both" else [])
              + list(replica_args))
            for i in range(n_replicas))
        # each replica writes its own trace PART file next to the
        # supervisor's: N processes appending to one file would interleave
        # mid-line; run_fleet stitches the parts (skew-corrected) at drain
        if self.env.get("DLLAMA_TRACE"):
            for r in self.replicas:
                r.env = dict(self.env, DLLAMA_TRACE=self.trace_part(r))

    def trace_part(self, r: ReplicaProc):
        """The per-replica trace part file path (None: tracing off)."""
        base = self.env.get("DLLAMA_TRACE")
        return f"{base}.replica-{r.port}" if base else None

    def addresses(self) -> list:
        return [r.name for r in self.replicas]

    def _open_log(self, r: ReplicaProc):
        if not self.log_dir:
            return None  # inherit the supervisor's stderr
        os.makedirs(self.log_dir, exist_ok=True)
        return open(os.path.join(self.log_dir,
                                 f"replica-{r.index}.log"), "ab")

    def _spawn(self, r: ReplicaProc) -> None:
        """Start (or restart) one replica. Caller holds ``_lock``."""
        log = self._open_log(r)
        r.proc = subprocess.Popen(
            r.argv, env=r.env if r.env is not None else self.env,
            stdout=log, stderr=subprocess.STDOUT if log else None,
            start_new_session=True)  # own process group: a ^C at the
        #   supervisor's terminal must not SIGINT replicas mid-drain
        if log is not None:
            log.close()  # Popen holds its own fd

    def start(self) -> None:
        with self._lock:
            for r in self.replicas:
                self._spawn(r)

    @staticmethod
    def _probe_ready(host: str, port: int, timeout_s: float = 1.0) -> bool:
        try:
            conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
            try:
                conn.request("GET", "/ready")
                return conn.getresponse().status == 200
            finally:
                conn.close()
        except OSError:
            return False  # not up yet — the caller polls

    def wait_ready(self, timeout_s: float = 180.0) -> bool:
        """Block until EVERY replica answers /ready 200 (model loaded,
        scheduler up). A replica process that already exited fails fast —
        waiting out the full timeout on a crashed replica helps nobody."""
        deadline = time.monotonic() + timeout_s
        pending = list(self.replicas)
        while pending and time.monotonic() < deadline:
            still = []
            for r in pending:
                if r.proc is not None and r.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {r.name} exited with "
                        f"{r.proc.returncode} before becoming ready")
                if not self._probe_ready(r.host, r.port):
                    still.append(r)
            pending = still
            if pending:
                time.sleep(0.25)
        return not pending

    def poll_restart(self) -> int:
        """One supervision pass: restart every exited replica still under
        its restart budget. Returns the number restarted. The router
        keeps routing around the hole while the restart loads weights."""
        n = 0
        with self._lock:
            if self._draining:
                return 0  # exits during drain are the POINT, not crashes
            for r in self.replicas:
                if r.proc is None or r.proc.poll() is None:
                    continue
                if r.restarts >= self.max_restarts:
                    continue  # crash-looping: leave it down, the probe
                    #            loop keeps it out of rotation
                r.restarts += 1
                print(f"🔁 replica {r.name} exited "
                      f"({r.proc.returncode}); restart "
                      f"{r.restarts}/{self.max_restarts}", file=sys.stderr)
                self._spawn(r)
                n += 1
        return n

    def _supervision_loop(self, interval_s: float) -> None:
        while not self._stopped.is_set():
            self.poll_restart()
            self._stopped.wait(interval_s)

    def start_supervision(self, interval_s: float = 1.0) -> None:
        if self._supervision is not None:
            return
        self._supervision = threading.Thread(
            target=self._supervision_loop, args=(interval_s,),
            daemon=True, name="dllama-fleet-supervise")
        self._supervision.start()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """SIGTERM every replica (each runs its own graceful drain: /ready
        flips 503, in-flight requests finish) and wait; SIGKILL stragglers
        at the deadline. Returns True when every replica exited in time."""
        with self._lock:
            self._draining = True
        self._stopped.set()
        procs = [r.proc for r in self.replicas if r.proc is not None]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + timeout_s
        clean = True
        for p in procs:
            left = deadline - time.monotonic()
            try:
                p.wait(timeout=max(0.1, left))
            except subprocess.TimeoutExpired:
                clean = False
                p.kill()
                p.wait()
        return clean


def merge_fleet_trace(fleet: Fleet, state) -> int:
    """Stitch the per-replica trace part files into the supervisor's own
    (router) trace file, each shifted by the negated clock offset the
    probe loop estimated for that replica — this is what makes a replica's
    queue/prefill/decode spans nest under the router's proxy spans on one
    timeline despite monotonic-clock skew. Consumes the part files and
    returns the number of events merged; no-op when tracing is off."""
    base = observability.trace_path()
    if base is None:
        return 0
    offsets = {}
    if state is not None:
        offsets = {rep.name: rep.clock_offset_us()
                   for rep in state.replicas}
    parts = []
    for r in fleet.replicas:
        part = fleet.trace_part(r)
        if part and os.path.exists(part):
            # merge_trace_parts ADDS its delta to each ts: subtracting the
            # replica's offset moves its stamps onto the router's clock
            parts.append((part, -offsets.get(r.name, 0)))
    if not parts:
        return 0
    n = observability.merge_trace_parts(base, parts)
    for part, _ in parts:
        try:
            os.remove(part)
        except OSError:
            pass  # the events are already merged; a leftover part file
            #       is clutter, not a failure
    print(f"🧵 merged {n} replica trace event(s) from {len(parts)} part "
          f"file(s) into {base}", file=sys.stderr)
    return n


def run_fleet(args) -> None:
    """``cli fleet``: the whole local topology — N replicas + router —
    supervised until SIGTERM/SIGINT, then drained in order."""
    replica_args = []
    for extra in getattr(args, "replica_arg", None) or []:
        replica_args.extend(extra.split())
    # the router's --ckpt-interval rides every replica's argv as the
    # serve-side default cadence, so fleet-wide checkpointing is one flag;
    # an explicit --replica-arg '--ckpt-interval ...' later in the argv
    # wins (argparse keeps the last occurrence)
    if "--ckpt-interval" not in replica_args:
        replica_args = (["--ckpt-interval",
                         str(getattr(args, "ckpt_interval", 32))]
                        + replica_args)
    # --slo-classes rides every replica's argv the same way: one fleet
    # flag configures every lane, --replica-arg still overrides
    slo_spec = getattr(args, "slo_classes", None)
    if slo_spec and "--slo-classes" not in replica_args:
        replica_args = ["--slo-classes", slo_spec] + replica_args
    # --ts-interval too: one flag sets the whole fleet's history cadence
    # (router + every replica sampler), --replica-arg still overrides
    if "--ts-interval" not in replica_args:
        replica_args = (["--ts-interval",
                         str(getattr(args, "ts_interval", 1.0))]
                        + replica_args)
    # --prefill N --decode M carve the first N+M replicas into dedicated
    # disaggregation roles (the rest stay "both"); the router migrates
    # only when it can see at least one routable replica of EACH
    n_pre = getattr(args, "prefill", 0) or 0
    n_dec = getattr(args, "decode", 0) or 0
    if bool(n_pre) != bool(n_dec):
        raise SystemExit("--prefill and --decode go together: migration "
                         "needs at least one replica of each role")
    if n_pre + n_dec > args.replicas:
        raise SystemExit(f"--prefill {n_pre} + --decode {n_dec} exceeds "
                         f"--replicas {args.replicas}")
    roles = (["prefill"] * n_pre + ["decode"] * n_dec
             + ["both"] * (args.replicas - n_pre - n_dec))
    fleet = Fleet(
        args.model, args.tokenizer,
        n_replicas=args.replicas, base_port=args.base_port,
        host=args.replica_host, replica_args=replica_args,
        max_restarts=args.max_restarts, log_dir=args.log_dir,
        roles=roles)
    print(f"🚀 spawning {args.replicas} replicas on "
          f"{args.replica_host}:{args.base_port}..."
          f"{args.base_port + args.replicas - 1}"
          + (f" ({n_pre} prefill + {n_dec} decode + "
             f"{args.replicas - n_pre - n_dec} both)" if n_pre else ""))
    fleet.start()
    state = None
    try:
        if not fleet.wait_ready(args.ready_timeout):
            raise RuntimeError(
                f"fleet not ready within {args.ready_timeout:.0f}s")
        fleet.start_supervision()
        state = router_mod.state_from_args(args, fleet.addresses())
        observability.emit_process_name("router")
        state.probe_once()
        state.start_probes()
        srv = router_mod.create_router_server(
            state, host=args.host, port=args.port)

        def _drain(_signum=None, _frame=None):
            # off the signal frame: drain blocks up to --drain-timeout and
            # srv.shutdown blocks until serve_forever returns
            print(f"⛔ draining fleet (up to {args.drain_timeout:.0f}s) ...",
                  file=sys.stderr)

            def _run():
                fleet.drain(args.drain_timeout)
                state.stop_probes()
                srv.shutdown()

            threading.Thread(target=_run, daemon=True,
                             name="dllama-fleet-drain").start()

        try:
            signal.signal(signal.SIGTERM, _drain)
            signal.signal(signal.SIGINT, _drain)
        except ValueError:
            pass  # not the main thread (embedded/test use): no signal hook
        print(f"🛰️  fleet front door on {args.host}:{args.port} -> "
              f"{', '.join(fleet.addresses())}")
        srv.serve_forever()
    finally:
        # belt over braces: serve_forever exits via drain in the normal
        # path, but a startup failure must never orphan replica processes
        fleet.drain(timeout_s=min(5.0, args.drain_timeout))
        # replicas are down (their trace files are final): stitch the
        # parts into the one merged fleet trace
        merge_fleet_trace(fleet, state)
