"""Per-request lifecycle primitives for the serving stack: deadlines,
cancellation tokens, bounded admission, and a scheduler supervisor.

PR 1's continuous batcher made the server fast; this layer makes it bounded
under failure. Every way a request can end other than "finished" is a typed
:class:`LifecycleError` carrying the HTTP status the handler should speak
(429 queue overflow, 503 draining/scheduler-crash, 504 deadline), so no
client ever observes an unbounded wait:

* :class:`Deadline` — wall-clock budget from submit, enforced by the decode
  loops BETWEEN chunks (a row never holds its slot past one chunk after
  expiry).
* :class:`CancelToken` — cooperative cancel (client disconnect, shutdown);
  the scheduler releases a cancelled row's slot at the next chunk boundary.
* :class:`AdmissionGate` — bounded in-flight counter: overflow is rejected
  NOW with 429 + Retry-After instead of queuing unboundedly, and
  ``begin_drain`` flips the gate to 503 for SIGTERM graceful shutdown.
* :class:`Supervisor` — owns the scheduler thread: a crash runs the
  ``on_crash`` hook (fail in-flight slots 503) and restarts the loop, so one
  poisoned window can never leave every later ``submit()`` hanging on a dead
  daemon.
* :class:`KVBudget` — the batcher's KV admission accountant: per-bucket
  residency and token-slot reservations against the session's modeled HBM
  budget, published as gauges. In paged mode it additionally OWNS the page
  allocator (free list + per-page refcounts, ``attach_pages``) so page-level
  occupancy is serving-side truth.
"""

from __future__ import annotations

import threading
import time

from .. import observability
from ..analysis.sanitize import guarded_by
from ..runtime import paged_kv

# Module-level metric handles against the shared default registry: created at
# import so every series is visible on /metrics from the first scrape, not
# only after its first failure.
_REG = observability.default_registry()
_M_REJECTIONS = _REG.counter(
    "dllama_admission_rejections_total",
    "Requests rejected at the admission gate, by reason",
    ("reason",))
_M_CRASHES = _REG.counter(
    "dllama_scheduler_crashes_total",
    "Supervised scheduler thread crashes (each one restarts the loop)")
_M_DEADLINES = _REG.counter(
    "dllama_deadline_expirations_total",
    "Requests whose wall-clock budget (--request-timeout) expired")
_M_INFLIGHT = _REG.gauge(
    "dllama_inflight_requests",
    "Requests currently admitted past the gate")
_M_CLASS_INFLIGHT = _REG.gauge(
    "dllama_class_inflight",
    "Requests currently admitted past the gate, by SLO class",
    ("slo_class",))
_M_CLASS_REJECTIONS = _REG.counter(
    "dllama_class_rejections_total",
    "Requests rejected at the admission gate, by SLO class and reason",
    ("slo_class", "reason"))
_M_KV_RESERVED = _REG.gauge(
    "dllama_kv_tokens_reserved",
    "KV token-slots reserved against the session's modeled HBM budget")
_M_KV_BUDGET = _REG.gauge(
    "dllama_kv_tokens_budget",
    "The session's modeled HBM budget in KV token-slots (max_batch*seq_len)")
_M_KV_ROWS = _REG.gauge(
    "dllama_kv_bucket_rows",
    "Rows resident per KV bucket context length",
    ("bucket",))
_M_KV_PAGES = _REG.gauge(
    "dllama_kv_pages",
    "Paged-KV arena pages by state (free / cached / held / reserved)",
    ("state",))
_M_KV_PAGES_TOTAL = _REG.gauge(
    "dllama_kv_pages_total",
    "Usable pages in the paged-KV arena (scratch page excluded)")


class LifecycleError(RuntimeError):
    """A request ended by lifecycle policy rather than by decoding.

    ``http_status``/``retry_after_s`` tell the handler what to speak; the
    message is the client-facing error text.
    """

    http_status = 500
    retry_after_s: float = None


class QueueFull(LifecycleError):
    """Admission rejected: the bounded queue is at capacity (HTTP 429).

    With SLO classes the rejection is lane-scoped: ``slo_class`` names the
    lane that overflowed and ``retry_after_s`` is computed from THAT lane's
    service-time EWMA and depth, so a saturated batch lane tells its clients
    to back off for minutes while interactive clients keep sub-second
    retry hints."""

    http_status = 429

    def __init__(self, depth: int, capacity: int, retry_after_s: float,
                 slo_class: str = None):
        lane = f" in the {slo_class!r} lane" if slo_class else ""
        super().__init__(
            f"server at capacity ({depth}/{capacity} requests in flight"
            f"{lane}); retry later")
        self.retry_after_s = retry_after_s
        self.slo_class = slo_class


class ServerDraining(LifecycleError):
    """Admission rejected: the server is draining for shutdown (HTTP 503)."""

    http_status = 503
    retry_after_s = 30.0

    def __init__(self):
        super().__init__("server is draining for shutdown")


class SchedulerCrashed(LifecycleError):
    """The scheduler thread died with this request in flight (HTTP 503).
    The supervisor restarts the thread; the REQUEST is not retried — replay
    is the client's call, not the server's."""

    http_status = 503
    retry_after_s = 1.0

    def __init__(self, cause: BaseException):
        super().__init__(f"scheduler crashed mid-request: {cause!r}; "
                         "scheduler restarted, retry the request")
        self.cause = cause


class DeadlineExceeded(LifecycleError):
    """The request's wall-clock budget expired mid-decode (HTTP 504)."""

    http_status = 504

    def __init__(self, budget_s: float):
        super().__init__(
            f"request exceeded its {budget_s:.1f}s deadline (--request-"
            "timeout); partial output discarded, slot released")
        self.budget_s = budget_s
        _M_DEADLINES.inc()
        observability.flight_recorder().record(
            "deadline", budget_s=round(budget_s, 3))


class RequestCancelled(LifecycleError):
    """The client went away (or shutdown forced the row out); no response
    channel exists, the error just resolves the slot's waiter."""

    def __init__(self, reason: str):
        super().__init__(f"request cancelled: {reason}")
        self.reason = reason


class Deadline:
    """Wall-clock budget counted from construction (i.e. from submit)."""

    __slots__ = ("budget_s", "expires_at")

    def __init__(self, budget_s: float):
        self.budget_s = budget_s
        self.expires_at = time.monotonic() + budget_s

    @classmethod
    def start(cls, budget_s) -> "Deadline":
        """None/0/negative budget means no deadline."""
        return cls(budget_s) if budget_s and budget_s > 0 else None

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def error(self) -> DeadlineExceeded:
        return DeadlineExceeded(self.budget_s)


class CancelToken:
    """Cooperative cancellation flag, set once with a reason."""

    def __init__(self):
        self._event = threading.Event()
        self.reason: str = None

    def cancel(self, reason: str) -> None:
        if not self._event.is_set():
            self.reason = reason
            self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def error(self) -> RequestCancelled:
        return RequestCancelled(self.reason or "cancelled")


#: the SLO classes the server speaks. A request names its lane with the
#: ``serving/protocol.HDR_CLASS`` hop header; anything else is a 400,
#: never silently defaulted.
SLO_CLASSES = ("interactive", "batch")


class SLOClass:
    """Per-lane admission policy: queue depth, deadline, residency cap.

    ``depth`` bounds how many requests of this class may be in flight at
    once (<=0: inherit the gate's total capacity). ``deadline_s`` is the
    class's default wall-clock budget when the server has no global
    ``--request-timeout`` (<=0: none). ``max_resident`` caps how many
    decode-pool rows the class may hold resident at once (<=0: unbounded);
    the batcher enforces it at admission and it is what makes a batch lane
    *preemptible* — rows beyond interactive's needs are reclaimable.
    """

    __slots__ = ("name", "depth", "deadline_s", "max_resident",
                 "ttft_ms", "tpot_ms", "err_rate")

    def __init__(self, name: str, depth: int = 0, deadline_s: float = 0.0,
                 max_resident: int = 0, ttft_ms: float = 0.0,
                 tpot_ms: float = 0.0, err_rate: float = 0.0):
        self.name = name
        self.depth = int(depth)
        self.deadline_s = float(deadline_s)
        self.max_resident = int(max_resident)
        # burn-rate SLO targets (obsv.burnrate): p95 TTFT/TPOT in ms and
        # the error-fraction budget; 0 = no target, no alert series
        self.ttft_ms = float(ttft_ms)
        self.tpot_ms = float(tpot_ms)
        self.err_rate = float(err_rate)

    def to_dict(self) -> dict:
        return {"depth": self.depth, "deadline_s": self.deadline_s,
                "max_resident": self.max_resident,
                "ttft_ms": self.ttft_ms, "tpot_ms": self.tpot_ms,
                "err_rate": self.err_rate}


def parse_slo_classes(spec: str) -> dict:
    """Parse ``--slo-classes`` into {class_name: SLOClass}.

    Grammar (classes separated by ``;``)::

        interactive:depth=48,deadline=30,ttft=500;batch:depth=16,resident=2

    ``ttft=``/``tpot=`` (p95 targets in ms) and ``err=`` (error-fraction
    budget) are the burn-rate SLO targets the obsv alert engine evaluates;
    left unset (0) a signal simply has no alert. Every class in
    :data:`SLO_CLASSES` gets an entry (unnamed classes get defaults), so
    callers never KeyError on a valid class name."""
    classes = {name: SLOClass(name) for name in SLO_CLASSES}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, rest = part.partition(":")
        name = name.strip()
        if name not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {name!r} (known: {SLO_CLASSES})")
        cls = classes[name]
        for kv in filter(None, (s.strip() for s in rest.split(","))):
            if "=" not in kv:
                raise ValueError(f"bad SLO option {kv!r} in {part!r}")
            k, v = (s.strip() for s in kv.split("=", 1))
            if k == "depth":
                cls.depth = int(v)
            elif k == "deadline":
                cls.deadline_s = float(v)
            elif k == "resident":
                cls.max_resident = int(v)
            elif k == "ttft":
                cls.ttft_ms = float(v)
            elif k == "tpot":
                cls.tpot_ms = float(v)
            elif k == "err":
                cls.err_rate = float(v)
            else:
                raise ValueError(
                    f"unknown SLO option {k!r} (want depth/deadline/"
                    "resident/ttft/tpot/err)")
    return classes


@guarded_by("_lock", "_inflight", "_draining", "_service_ewma_s",
            "_class_inflight", "_class_ewma_s")
class AdmissionGate:
    """Bounded in-flight request counter with drain support.

    ``acquire`` either admits (incrementing the in-flight count) or raises
    :class:`QueueFull` / :class:`ServerDraining` — it NEVER blocks, which is
    the whole point: backpressure is a fast typed rejection the client can
    act on, not an invisible queue. ``retry_after`` scales with how loaded
    the gate is, seeded by an EWMA of recent request service times.

    With ``classes`` (see :func:`parse_slo_classes`) the gate keeps one
    bounded lane per SLO class on top of the total capacity: a class whose
    lane is full 429s with a *class-scoped* Retry-After (that lane's EWMA x
    that lane's depth) even while the other lane still admits. The bare
    ``acquire()``/``release()`` calls keep their pre-class behavior (they
    ride the "interactive" lane), so single-class callers are untouched.
    """

    def __init__(self, capacity: int, flight=None, classes: dict = None):
        self.capacity = max(1, capacity)
        self.classes = classes if classes is not None else parse_slo_classes("")
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight = 0
        self._draining = False
        self._service_ewma_s = 1.0  # optimistic prior; updated per release
        self._class_inflight = {name: 0 for name in self.classes}
        self._class_ewma_s = {name: 1.0 for name in self.classes}
        # set-once black box (observability.FlightRecorder); every admission
        # decision lands in its ring so a crash dump shows what the gate was
        # doing in the final seconds
        self._flight = flight if flight is not None \
            else observability.flight_recorder()

    @property
    def depth(self) -> int:
        return self._inflight

    def class_depths(self) -> dict:
        """{class: in-flight count} — the readiness probe's lane view."""
        with self._lock:
            return dict(self._class_inflight)

    def class_capacity(self, slo_class: str) -> int:
        """The lane's effective bound: its configured depth, else the
        gate's total capacity."""
        cls = self.classes.get(slo_class)
        return cls.depth if cls is not None and cls.depth > 0 \
            else self.capacity

    def deadline_for(self, slo_class: str) -> float:
        """The lane's default wall-clock budget (0.0: none configured)."""
        cls = self.classes.get(slo_class)
        return cls.deadline_s if cls is not None else 0.0

    @property
    def draining(self) -> bool:
        return self._draining

    def retry_after_s(self, slo_class: str = None) -> float:
        """Seconds a 429'd client should wait: one EWMA service time per
        queued request ahead of it, floored at 1s so clients never busy-spin.
        Class-scoped when ``slo_class`` names a lane — a saturated batch
        lane's backoff grows with *batch* service times, not the fleet's."""
        if slo_class in self._class_ewma_s:
            return max(1.0, self._class_ewma_s[slo_class]
                       * self._class_inflight[slo_class])
        return max(1.0, self._service_ewma_s * self._inflight)

    def acquire(self, slo_class: str = "interactive") -> float:
        """Admit one request into its class lane; returns its admit
        timestamp (pass back to ``release`` for the service-time EWMA)."""
        with self._lock:
            if self._draining:
                _M_REJECTIONS.inc(reason="draining")
                _M_CLASS_REJECTIONS.inc(slo_class=slo_class,
                                        reason="draining")
                self._flight.record("reject", reason="draining")
                raise ServerDraining()
            lane_cap = self.class_capacity(slo_class)
            lane_depth = self._class_inflight.get(slo_class, 0)
            if self._inflight >= self.capacity or lane_depth >= lane_cap:
                _M_REJECTIONS.inc(reason="queue_full")
                _M_CLASS_REJECTIONS.inc(slo_class=slo_class,
                                        reason="queue_full")
                self._flight.record("reject", reason="queue_full",
                                    depth=self._inflight,
                                    slo_class=slo_class)
                if lane_depth >= lane_cap:
                    raise QueueFull(lane_depth, lane_cap,
                                    self.retry_after_s(slo_class), slo_class)
                raise QueueFull(self._inflight, self.capacity,
                                self.retry_after_s())
            self._inflight += 1
            if slo_class in self._class_inflight:
                self._class_inflight[slo_class] += 1
                _M_CLASS_INFLIGHT.set(self._class_inflight[slo_class],
                                      slo_class=slo_class)
            _M_INFLIGHT.set(self._inflight)
            self._flight.record("admit", depth=self._inflight,
                                slo_class=slo_class)
            return time.monotonic()

    def release(self, admitted_at: float = None,
                slo_class: str = "interactive") -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            _M_INFLIGHT.set(self._inflight)
            if slo_class in self._class_inflight:
                self._class_inflight[slo_class] = max(
                    0, self._class_inflight[slo_class] - 1)
                _M_CLASS_INFLIGHT.set(self._class_inflight[slo_class],
                                      slo_class=slo_class)
            if admitted_at is not None:
                dt = max(0.0, time.monotonic() - admitted_at)
                self._service_ewma_s += 0.2 * (dt - self._service_ewma_s)
                if slo_class in self._class_ewma_s:
                    self._class_ewma_s[slo_class] += 0.2 * (
                        dt - self._class_ewma_s[slo_class])
            if self._inflight == 0:
                self._idle.notify_all()

    def begin_drain(self) -> None:
        """Stop admitting; in-flight requests keep running."""
        with self._lock:
            self._draining = True

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until nothing is in flight (or timeout). True when idle —
        the SIGTERM drain's exit condition."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(left)
            return True


@guarded_by("_lock", "_reserved", "_rows", "pages")
class KVBudget:
    """Serving-side KV admission accountant for a BatchSession.

    The session enforces its own modeled HBM budget (``can_admit``); this
    mirror keeps the SERVER's view — reservations against the budget and
    rows resident per context bucket — and publishes it as gauges
    (``dllama_kv_tokens_reserved``, ``dllama_kv_bucket_rows{bucket}``), so
    an operator can see at a glance how many short rows the bucketed pools
    are packing into the slab the uniform layout spends on max_batch
    full-context rows. The runtime never imports serving: the session takes
    this object duck-typed via ``batch_session(kv_budget=...)``.

    Thread-safe; the scheduler thread mutates it while the metrics thread
    reads. All methods are O(1).
    """

    def __init__(self, total_tokens: int):
        self.total_tokens = max(1, int(total_tokens))
        self._lock = threading.Lock()
        self._reserved = 0
        self._rows: dict = {}  # bucket ctx -> resident rows
        self.pages: paged_kv.PageAllocator = None  # paged mode (attach_pages)
        _M_KV_BUDGET.set(self.total_tokens)
        _M_KV_RESERVED.set(0)

    def attach_pages(self, num_pages: int,
                     page_tokens: int) -> "paged_kv.PageAllocator":
        """Adopt a paged session's free list + refcounts: the allocator
        LIVES here so the serving accountant (and its gauges) always see
        page-level truth, while the runtime session drives it duck-typed.
        Called by BatchSession at construction in paged mode; a scheduler
        restart re-attaches a fresh allocator for its fresh arena."""
        with self._lock:
            self.pages = paged_kv.PageAllocator(
                num_pages, page_tokens, on_stats=self._publish_pages)
            self._publish_pages(self.pages.stats())
            return self.pages

    @staticmethod
    def _publish_pages(s: dict) -> None:
        _M_KV_PAGES_TOTAL.set(s["pages_total"])
        _M_KV_PAGES.set(s["pages_free"], state="free")
        _M_KV_PAGES.set(s["pages_cached"], state="cached")
        _M_KV_PAGES.set(s["pages_held"], state="held")
        _M_KV_PAGES.set(s["pages_reserved"], state="reserved")

    def page_stats(self) -> dict:
        """The attached allocator's occupancy snapshot ({} in slab modes)."""
        with self._lock:
            return self.pages.stats() if self.pages is not None else {}

    @property
    def reserved(self) -> int:
        return self._reserved

    def rows_by_bucket(self) -> dict:
        with self._lock:
            return dict(self._rows)

    def can_fit(self, tokens: int) -> bool:
        with self._lock:
            return self._reserved + tokens <= self.total_tokens

    def reserve(self, tokens: int) -> None:
        with self._lock:
            self._reserved += tokens
            _M_KV_RESERVED.set(self._reserved)

    def release(self, tokens: int) -> None:
        with self._lock:
            self._reserved = max(0, self._reserved - tokens)
            _M_KV_RESERVED.set(self._reserved)

    def place(self, bucket: int) -> None:
        with self._lock:
            self._rows[bucket] = self._rows.get(bucket, 0) + 1
            _M_KV_ROWS.set(self._rows[bucket], bucket=str(bucket))

    def unplace(self, bucket: int) -> None:
        with self._lock:
            self._rows[bucket] = max(0, self._rows.get(bucket, 0) - 1)
            _M_KV_ROWS.set(self._rows[bucket], bucket=str(bucket))

    def migrate(self, old_bucket: int, new_bucket: int) -> None:
        """A row moved buckets: occupancy shifts, reservation unchanged
        (admission reserved the worst-case bucket up front)."""
        with self._lock:
            self._rows[old_bucket] = max(0, self._rows.get(old_bucket, 0) - 1)
            self._rows[new_bucket] = self._rows.get(new_bucket, 0) + 1
            _M_KV_ROWS.set(self._rows[old_bucket], bucket=str(old_bucket))
            _M_KV_ROWS.set(self._rows[new_bucket], bucket=str(new_bucket))


@guarded_by("_lock", "_thread", "crash_count", "_stopped")
class Supervisor:
    """Owns a daemon thread running ``target`` and restarts it on crash.

    ``target`` is a long-running loop (the server scheduler); a normal
    return ends supervision (the drain path). An exception runs
    ``on_crash(exc)`` — which must fail the in-flight work so no waiter
    hangs — then restarts ``target`` after a short pause. ``alive`` is the
    readiness probe's scheduler-liveness answer.
    """

    def __init__(self, target, on_crash, name: str = "supervised",
                 restart_delay_s: float = 0.05, max_restarts: int = None):
        self._target = target
        self._on_crash = on_crash
        self._name = name
        self._restart_delay_s = restart_delay_s
        self._max_restarts = max_restarts  # None = unlimited
        self._lock = threading.Lock()
        self._thread: threading.Thread = None
        self.crash_count = 0
        self._stopped = False

    def start(self) -> None:
        """Idempotent: starts the loop thread on first call."""
        with self._lock:
            if self._thread is not None or self._stopped:
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=self._name)
            self._thread.start()

    def _run(self) -> None:
        while not self._stopped:
            try:
                self._target()
                return  # clean exit: drain finished
            except BaseException as e:  # noqa: BLE001 — supervision IS the catch
                with self._lock:
                    self.crash_count += 1
                    crashes = self.crash_count
                _M_CRASHES.inc()
                observability.flight_recorder().record(
                    "crash", target=self._name, error=repr(e)[:200],
                    crash_count=crashes)
                try:
                    self._on_crash(e)
                except Exception:  # noqa: BLE001 — crash hook must not kill
                    pass  # the supervisor; liveness beats accounting here
                if (self._max_restarts is not None
                        and crashes > self._max_restarts):
                    return
                time.sleep(self._restart_delay_s)

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self) -> None:
        """Stop restarting (the running iteration finishes on its own)."""
        with self._lock:
            self._stopped = True
