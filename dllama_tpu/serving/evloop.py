"""A stdlib ``selectors`` event loop + HTTP plumbing for the fleet front
door.

The router's data plane (``serving/router.py``) runs on this loop: one
thread, non-blocking sockets, and one generator coroutine per connection.
Coroutines use ``yield from`` composition and suspend by yielding a
syscall object the loop interprets:

* ``_Wait(fd, events, deadline, edge)`` — park until the fd is ready or
  the deadline passes.  Expiry is delivered by **throwing**
  :class:`LoopTimeout` (an ``OSError`` subclass) into the coroutine, so
  every ported ``except OSError`` failure path treats a missed deadline
  exactly like a connect error — per-edge deadlines without new error
  plumbing.  ``edge`` names which budget expired (``header``,
  ``connect``, ``first_byte``, ``stall``, ``client_write``) for the
  error message.
* ``_Sleep(deadline)`` — a pure timer (the bench's drip writers).
* ``_Thread(fn)`` — run ``fn`` on a worker thread and resume with its
  result; the loop's blocking control-plane escapes (federation scrapes)
  ride this instead of stalling the data plane.

Deadline/readiness race: a task with BOTH pending bytes and an expired
deadline always gets the bytes — :func:`recv_some` tries the
non-blocking ``recv`` *before* parking, and the run loop delivers fd
readiness before timer expiry within one poll round.  That ordering is
what makes "``[DONE]`` arrived in the same read as the stall-timeout
expiry" a completed stream instead of a spurious failover (pinned by
tests/test_router_loop.py).

Backpressure is structural: a relay coroutine holds at most one chunk
(<= 64 KiB) in hand and cannot read more from its upstream until the
client write completes, so a slow client pauses its upstream read
instead of growing router RSS.  The client-write deadline is the hard
kill for clients stalled past the idle budget.

Only the handful of leaf primitives here (``recv_some`` / ``send_all`` /
``dial`` / ``_accept_nb`` / the pool's liveness peek) touch raw
socket calls; every socket is non-blocking, so they never block — they
yield to the loop on EAGAIN.  Everything above them is annotated
``@loop_callback`` and dllama-check's LOOP-001 forbids the blocking
shortlist inside those functions.
"""

from __future__ import annotations

import errno
import heapq
import http.client
import itertools
import selectors
import socket
import sys
import threading
import time
from collections import deque

from dllama_tpu.analysis.sanitize import guarded_by, loop_callback

#: per-recv read size — also the write-buffer bound per connection: a relay
#: never holds more than one chunk between upstream read and client write
CHUNK = 65536

#: largest request/response head the loop will buffer before giving up —
#: a slow-loris dribbling headers hits the header deadline first, but a
#: fast sender of endless headers must be bounded by size too
MAX_HEAD = 65536

#: grace window after a stall-budget expiry: one short extra read so bytes
#: already in flight at the expiry instant (the [DONE]-races-the-budget
#: edge) are delivered instead of discarded — a real stall just pays this
#: once before the failover
STALL_DRAIN_GRACE_S = 0.1


class LoopTimeout(OSError):
    """A per-edge deadline expired.  An ``OSError`` so the ported retry /
    failover paths (written for connect errors and torn reads) handle a
    missed deadline without new except clauses."""

    def __init__(self, edge: str):
        super().__init__(f"deadline expired at edge {edge!r}")
        self.edge = edge


class ProtocolError(OSError):
    """Malformed HTTP from a peer.  An ``OSError`` for the same reason as
    :class:`LoopTimeout`: a garbled upstream is a dead upstream."""


class HttpError(Exception):
    """A client request the server refuses with ``status`` (431/413/...)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


# ---------------------------------------------------------------------------
# syscalls
# ---------------------------------------------------------------------------

class _Wait:
    __slots__ = ("fd", "events", "deadline", "edge")

    def __init__(self, fd: int, events: int, deadline, edge: str):
        self.fd, self.events = fd, events
        self.deadline, self.edge = deadline, edge


class _Sleep:
    __slots__ = ("deadline",)

    def __init__(self, deadline: float):
        self.deadline = deadline


class _Thread:
    __slots__ = ("fn",)

    def __init__(self, fn):
        self.fn = fn


def sleep(seconds: float):
    """Coroutine: suspend for ``seconds`` without blocking the loop."""
    yield _Sleep(time.monotonic() + seconds)


def run_in_thread(fn):
    """Coroutine: run blocking ``fn`` on a worker thread, resume with its
    return value (or its exception re-raised here).  The escape hatch for
    control-plane work that legitimately blocks (federation scrapes)."""
    result = yield _Thread(fn)
    return result


class _Task:
    __slots__ = ("gen", "wait_fd", "wait_token", "done")

    def __init__(self, gen):
        self.gen = gen
        self.wait_fd = None    # fd currently registered with the selector
        self.wait_token = 0    # invalidates stale timer entries on resume
        self.done = False


@guarded_by("_calls_lock", "_calls")
class Loop:
    """The scheduler: a selector, a timer heap, a ready queue and a
    cross-thread call queue drained via a socketpair waker.  Everything
    except :meth:`call_threadsafe` / :meth:`stop` runs on the loop
    thread."""

    def __init__(self):
        self._selector = selectors.DefaultSelector()
        self._timers: list = []      # (deadline, seq, task, token, edge|None)
        self._seq = itertools.count()
        self._ready: deque = deque()  # (task, value, exc) to resume this tick
        self._tasks: set = set()
        self._stopping = False
        self._calls_lock = threading.Lock()
        self._calls: deque = deque()  # cross-thread callables
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._waker_w.setblocking(False)
        self._selector.register(self._waker_r, selectors.EVENT_READ, None)

    # -- cross-thread entry points ----------------------------------------

    def call_threadsafe(self, fn) -> None:
        """Queue ``fn`` to run on the loop thread and wake the selector."""
        with self._calls_lock:
            self._calls.append(fn)
        try:
            self._waker_w.send(b"\x00")
        except OSError:
            pass  # waker full (a wake is already pending) or loop gone

    def stop(self) -> None:
        self._stopping = True

    # -- scheduling --------------------------------------------------------

    def spawn(self, gen) -> _Task:
        """Register generator ``gen`` as a task and start it this tick."""
        task = _Task(gen)
        self._tasks.add(task)
        self._ready.append((task, None, None))
        return task

    def _finish(self, task: _Task) -> None:
        task.done = True
        if task.wait_fd is not None:
            try:
                self._selector.unregister(task.wait_fd)
            except (KeyError, ValueError, OSError):
                pass  # fd already closed/unregistered — nothing to undo
            task.wait_fd = None
        self._tasks.discard(task)

    def _step(self, task: _Task, value, exc) -> None:
        """Resume ``task`` once and act on the syscall it yields."""
        if task.done:
            return
        try:
            if exc is not None:
                syscall = task.gen.throw(exc)
            else:
                syscall = task.gen.send(value)
        except StopIteration:
            self._finish(task)
            return
        except OSError:
            # a connection task ending on socket error/timeout is the
            # normal teardown path, not a loop problem
            self._finish(task)
            return
        except Exception as e:  # a task bug must never kill the loop
            print(f"evloop: task crashed: {e!r}", file=sys.stderr)
            self._finish(task)
            return
        if isinstance(syscall, _Wait):
            task.wait_fd = syscall.fd
            try:
                self._selector.register(syscall.fd, syscall.events, task)
            except (KeyError, ValueError, OSError) as e:
                task.wait_fd = None
                self._ready.append((task, None,
                                    OSError(f"wait on dead fd: {e}")))
                return
            if syscall.deadline is not None:
                heapq.heappush(self._timers,
                               (syscall.deadline, next(self._seq), task,
                                task.wait_token, syscall.edge))
        elif isinstance(syscall, _Sleep):
            heapq.heappush(self._timers,
                           (syscall.deadline, next(self._seq), task,
                            task.wait_token, None))
        elif isinstance(syscall, _Thread):
            self._offload(task, syscall.fn)
        else:
            # bare `yield`: cooperative reschedule on the next tick
            self._ready.append((task, None, None))

    def _offload(self, task: _Task, fn) -> None:
        def runner():
            try:
                res, err = fn(), None
            except Exception as e:  # delivered into the coroutine below
                res, err = None, e
            self.call_threadsafe(lambda: self._step(task, res, err))
        threading.Thread(target=runner, daemon=True,
                         name="evloop-offload").start()

    # -- the run loop ------------------------------------------------------

    def _drain_calls(self) -> None:
        while True:
            with self._calls_lock:
                if not self._calls:
                    return
                fn = self._calls.popleft()
            fn()

    def _resume_timer(self, task: _Task, edge) -> None:
        if task.wait_fd is not None:
            try:
                self._selector.unregister(task.wait_fd)
            except (KeyError, ValueError, OSError):
                pass  # fd vanished with its socket — the throw below ends it
            task.wait_fd = None
        task.wait_token += 1
        if edge is None:
            self._step(task, None, None)        # sleep completed
        else:
            self._step(task, None, LoopTimeout(edge))

    def run(self) -> None:
        """Drive tasks until :meth:`stop`.  On exit every live task is
        closed (GeneratorExit runs its ``finally`` blocks, closing its
        sockets)."""
        try:
            while not self._stopping:
                while self._ready and not self._stopping:
                    task, value, exc = self._ready.popleft()
                    self._step(task, value, exc)
                if self._stopping:
                    break
                timeout = None
                if self._ready:
                    timeout = 0.0
                elif self._timers:
                    timeout = max(0.0,
                                  self._timers[0][0] - time.monotonic())
                events = self._selector.select(timeout)
                # fd readiness is delivered BEFORE timer expiry: bytes that
                # arrived in the same poll round as a deadline win the race
                for key, _mask in events:
                    if key.data is None:
                        try:
                            while self._waker_r.recv(4096):
                                pass
                        except OSError:
                            pass  # drained (EAGAIN) — the wake did its job
                        continue
                    task = key.data
                    try:
                        self._selector.unregister(key.fileobj)
                    except (KeyError, ValueError, OSError):
                        pass  # racing close; resuming the task is still right
                    task.wait_fd = None
                    task.wait_token += 1
                    self._step(task, None, None)
                now = time.monotonic()
                while self._timers and self._timers[0][0] <= now:
                    _dl, _seq, task, token, edge = heapq.heappop(self._timers)
                    if task.done or token != task.wait_token:
                        continue  # the wait this timer guarded already ended
                    self._resume_timer(task, edge)
                self._drain_calls()
        finally:
            for task in list(self._tasks):
                try:
                    task.gen.close()
                except Exception as e:  # a finally-block bug; keep closing
                    print(f"evloop: task close failed: {e!r}",
                          file=sys.stderr)
                self._finish(task)
            try:
                self._selector.unregister(self._waker_r)
            except (KeyError, ValueError, OSError):
                pass  # selector may already be torn down
            _close_quiet(self._waker_r)
            _close_quiet(self._waker_w)
            self._selector.close()


# ---------------------------------------------------------------------------
# non-blocking leaf primitives (the audited raw-socket surface; deliberately
# NOT @loop_callback — see the module docstring)
# ---------------------------------------------------------------------------

def _close_quiet(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass  # closing a dead socket is still closed


def _accept_nb(listen_sock):
    """One non-blocking accept: (sock, addr) or None when drained."""
    try:
        return listen_sock.accept()
    except (BlockingIOError, InterruptedError):
        return None


def recv_some(sock, deadline=None, edge: str = "read", n: int = CHUNK):
    """Coroutine: the next <= ``n`` bytes (b'' on EOF).  Tries the
    non-blocking recv FIRST, so already-delivered bytes beat an
    already-expired deadline."""
    while True:
        try:
            return sock.recv(n)
        except (BlockingIOError, InterruptedError):
            yield _Wait(sock.fileno(), selectors.EVENT_READ, deadline, edge)


def send_all(sock, data: bytes, deadline=None, edge: str = "client_write"):
    """Coroutine: write all of ``data``, parking on EAGAIN.  The deadline
    is the hard kill for peers that stop draining their socket."""
    view = memoryview(data)
    while view:
        try:
            sent = sock.send(view)
        except (BlockingIOError, InterruptedError):
            yield _Wait(sock.fileno(), selectors.EVENT_WRITE, deadline, edge)
            continue
        view = view[sent:]


def dial(addr, deadline=None, edge: str = "connect"):
    """Coroutine: a connected non-blocking TCP socket, or OSError /
    LoopTimeout(edge).  (Named ``dial``, not ``connect``: the blocking
    shortlist LOOP-001 enforces treats any ``connect(...)`` leaf as
    socket I/O, and this audited primitive is called FROM annotated
    callbacks.)"""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ok = False
    try:
        sock.setblocking(False)
        err = sock.connect_ex(addr)
        if err not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            raise OSError(err, f"connect to {addr}: {errno.errorcode.get(err, err)}")
        if err != 0:
            yield _Wait(sock.fileno(), selectors.EVENT_WRITE, deadline, edge)
            err = sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err != 0:
                raise OSError(
                    err, f"connect to {addr}: {errno.errorcode.get(err, err)}")
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not TCP (tests may hand in socketpairs) — fine unbatched
        ok = True
        return sock
    finally:
        if not ok:
            _close_quiet(sock)


# ---------------------------------------------------------------------------
# server-side HTTP
# ---------------------------------------------------------------------------

class Request:
    """One parsed client request (headers lowercased)."""

    __slots__ = ("method", "path", "version", "headers", "body", "keep_alive")

    def __init__(self, method, path, version, headers, body):
        self.method, self.path, self.version = method, path, version
        self.headers, self.body = headers, body
        conn_tok = headers.get("connection", "").lower()
        if version == "HTTP/1.1":
            self.keep_alive = conn_tok != "close"
        else:
            self.keep_alive = conn_tok == "keep-alive"

    def header(self, name: str, default=None):
        return self.headers.get(name.lower(), default)


@loop_callback
def read_request(sock, buf: bytearray, header_deadline=None,
                 body_deadline=None, max_body: int = 16 * 1024 * 1024):
    """Coroutine: the next Request off one client connection.

    Returns None on clean EOF before any byte (keep-alive close).  A
    peer that dribbles slower than ``header_deadline`` gets
    LoopTimeout("header") — the slow-loris kill.  Raises HttpError for
    requests the caller should answer with a 4xx, ProtocolError for
    garbage not worth answering."""
    while True:
        head_end = buf.find(b"\r\n\r\n")
        if head_end >= 0:
            break
        if len(buf) > MAX_HEAD:
            raise HttpError(431, "request head too large")
        data = yield from recv_some(sock, header_deadline, edge="header")
        if not data:
            if buf:
                raise ProtocolError("connection closed mid-request-head")
            return None
        buf += data
    head = bytes(buf[:head_end])
    del buf[:head_end + 4]
    lines = head.split(b"\r\n")
    parts = lines[0].decode("latin-1").split()
    if len(parts) != 3 or not parts[2].startswith("HTTP/"):
        raise ProtocolError(f"bad request line {lines[0][:80]!r}")
    method, target, version = parts
    headers: dict = {}
    for raw in lines[1:]:
        name, sep, value = raw.partition(b":")
        if not sep:
            raise ProtocolError(f"bad header line {raw[:80]!r}")
        headers[name.decode("latin-1").strip().lower()] = (
            value.decode("latin-1").strip())
    if "chunked" in headers.get("transfer-encoding", "").lower():
        raise HttpError(411, "chunked request bodies not supported")
    try:
        length = int(headers.get("content-length", "0") or "0")
    except ValueError:
        raise HttpError(400, "bad Content-Length")
    if length > max_body:
        raise HttpError(413, "request body too large")
    while len(buf) < length:
        data = yield from recv_some(sock, body_deadline or header_deadline,
                                    edge="header")
        if not data:
            raise ProtocolError("connection closed mid-request-body")
        buf += data
    body = bytes(buf[:length])
    del buf[:length]
    return Request(method, target, version, headers, body)


def response_bytes(status: int, headers: list, body: bytes = b"",
                   version: str = "HTTP/1.1") -> bytes:
    """One full HTTP response as bytes (headers is a list of (k, v) pairs
    so repeats — two Server-Timing lines — survive)."""
    reason = http.client.responses.get(status, "Unknown")
    lines = [f"{version} {status} {reason}"]
    for k, v in headers:
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body


# ---------------------------------------------------------------------------
# loop-native upstream HTTP client
# ---------------------------------------------------------------------------

class Upstream:
    """One upstream connection: request writer + response-head parser.
    The read buffer lives here so a keep-alive reuse keeps leftover
    bytes with the socket they came from."""

    def __init__(self, sock, host: str, port: int):
        self.sock = sock
        self.host, self.port = host, port
        self.buf = bytearray()

    def close(self) -> None:
        _close_quiet(self.sock)

    @loop_callback
    def request(self, method: str, path: str, headers: dict,
                body: bytes = b"", deadline=None):
        """Coroutine: send one request head + body."""
        body = body or b""
        lines = [f"{method} {path} HTTP/1.1",
                 f"Host: {self.host}:{self.port}",
                 "Accept-Encoding: identity"]
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        lines.append(f"Content-Length: {len(body)}")
        data = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body
        yield from send_all(self.sock, data, deadline, edge="connect")

    @loop_callback
    def get_response(self, deadline=None, edge: str = "first_byte"):
        """Coroutine: parse the response head; the deadline is the
        first-upstream-byte budget."""
        while True:
            head_end = self.buf.find(b"\r\n\r\n")
            if head_end >= 0:
                break
            if len(self.buf) > MAX_HEAD:
                raise ProtocolError("oversized upstream response head")
            data = yield from recv_some(self.sock, deadline, edge=edge)
            if not data:
                raise ProtocolError("upstream closed before response head")
            self.buf += data
        head = bytes(self.buf[:head_end])
        del self.buf[:head_end + 4]
        lines = head.split(b"\r\n")
        parts = lines[0].decode("latin-1").split(None, 2)
        if len(parts) < 2 or not parts[0].startswith("HTTP/"):
            raise ProtocolError(f"bad status line {lines[0][:80]!r}")
        try:
            status = int(parts[1])
        except ValueError:
            raise ProtocolError(f"bad status {parts[1]!r}")
        headers: dict = {}
        for raw in lines[1:]:
            name, sep, value = raw.partition(b":")
            if sep:
                headers[name.decode("latin-1").strip().lower()] = (
                    value.decode("latin-1").strip())
        return UpstreamResponse(self, parts[0], status, headers)


class UpstreamResponse:
    """Incremental body reader over an Upstream: Content-Length, chunked,
    or read-to-EOF framing, decided by the response head."""

    def __init__(self, up: Upstream, version: str, status: int,
                 headers: dict):
        self.up = up
        self.version = version
        self.status = status
        self.headers = headers
        te = headers.get("transfer-encoding", "")
        self._chunked = "chunked" in te.lower()
        self._remaining = None
        if not self._chunked:
            cl = headers.get("content-length")
            if cl is not None:
                try:
                    self._remaining = int(cl)
                except ValueError:
                    raise ProtocolError(f"bad upstream Content-Length {cl!r}")
        self._chunk_rem = 0
        self._chunk_state = "size"
        self._eof = self._remaining == 0
        self._clean = self._eof  # framing completed (vs torn/EOF-mode end)

    def getheader(self, name: str, default=None):
        return self.headers.get(name.lower(), default)

    @property
    def reusable(self) -> bool:
        """Safe to return this socket to the pool: the framed body was
        fully consumed and neither side asked to close."""
        return (self._clean
                and self.version == "HTTP/1.1"
                and "close" not in self.headers.get("connection", "").lower())

    def _take_buffered(self) -> bytes:
        """Decode whatever body bytes already sit in the read buffer."""
        buf = self.up.buf
        if self._eof:
            return b""
        if self._remaining is not None:
            take = min(len(buf), self._remaining)
            out = bytes(buf[:take])
            del buf[:take]
            self._remaining -= take
            if self._remaining == 0:
                self._eof = self._clean = True
            return out
        if self._chunked:
            return self._take_chunked()
        out = bytes(buf)  # EOF-delimited (SSE replicas send Connection: close)
        del buf[:]
        return out

    def _take_chunked(self) -> bytes:
        out = bytearray()
        buf = self.up.buf
        while not self._eof:
            if self._chunk_rem > 0:
                take = min(len(buf), self._chunk_rem)
                if not take:
                    break
                out += buf[:take]
                del buf[:take]
                self._chunk_rem -= take
                if self._chunk_rem == 0:
                    self._chunk_state = "crlf"
                continue
            if self._chunk_state == "crlf":
                if len(buf) < 2:
                    break
                del buf[:2]
                self._chunk_state = "size"
                continue
            if self._chunk_state == "size":
                nl = buf.find(b"\r\n")
                if nl < 0:
                    break
                size_field = bytes(buf[:nl]).split(b";", 1)[0].strip()
                del buf[:nl + 2]
                try:
                    size = int(size_field, 16)
                except ValueError:
                    raise ProtocolError(f"bad chunk size {size_field[:20]!r}")
                if size == 0:
                    self._chunk_state = "trailer"
                else:
                    self._chunk_rem = size
                continue
            # trailer: consume lines until the empty one ends the body
            nl = buf.find(b"\r\n")
            if nl < 0:
                break
            line = bytes(buf[:nl])
            del buf[:nl + 2]
            if not line:
                self._eof = self._clean = True
        return bytes(out)

    def try_read_now(self) -> bytes:
        """Non-blocking: decode pending bytes without suspending — the
        stall-expiry drain (data already delivered beats the budget)."""
        out = self._take_buffered()
        if out or self._eof:
            return out
        try:
            data = self.up.sock.recv(CHUNK)
        except (BlockingIOError, InterruptedError):
            return b""
        except OSError:
            self._eof = True
            return b""
        if not data:
            self._eof = True
            return b""
        self.up.buf += data
        return self._take_buffered()

    @loop_callback
    def read_some(self, deadline=None, edge: str = "stall"):
        """Coroutine: the next decoded body bytes; b'' at end of body.
        The deadline is the inter-byte budget (SSE stall detection)."""
        while True:
            out = self._take_buffered()
            if out or self._eof:
                return out
            data = yield from recv_some(self.up.sock, deadline, edge=edge)
            if not data:
                self._eof = True
                if self._remaining not in (None, 0) or (
                        self._chunked and not self._clean):
                    raise ProtocolError("upstream closed mid-body")
                return b""
            self.up.buf += data

    @loop_callback
    def read_all(self, deadline=None):
        """Coroutine: the whole remaining body."""
        parts = []
        while True:
            chunk = yield from self.read_some(deadline, edge="body")
            if not chunk:
                return b"".join(parts)
            parts.append(chunk)


class UpstreamPool:
    """Idle upstream sockets keyed by (host, port), loop-thread only.
    Only fully-drained framed responses return their socket here
    (:attr:`UpstreamResponse.reusable`); a liveness peek on checkout
    discards sockets the replica closed while idle."""

    def __init__(self, per_key: int = 8):
        self.per_key = per_key
        self._idle: dict = {}

    def get(self, host: str, port: int):
        bucket = self._idle.get((host, port))
        while bucket:
            sock = bucket.pop()
            try:
                pending = sock.recv(1, socket.MSG_PEEK)
            except (BlockingIOError, InterruptedError):
                return sock  # alive and quiet — the healthy idle state
            except OSError:
                _close_quiet(sock)
                continue
            # EOF (b"") or unsolicited bytes: either way not reusable
            _close_quiet(sock)
        return None

    def put(self, host: str, port: int, sock) -> None:
        bucket = self._idle.setdefault((host, port), [])
        if len(bucket) >= self.per_key:
            _close_quiet(sock)
            return
        bucket.append(sock)

    def close_all(self) -> None:
        for bucket in self._idle.values():
            for sock in bucket:
                _close_quiet(sock)
        self._idle.clear()


@loop_callback
def open_upstream(pool, host: str, port: int, deadline=None):
    """Coroutine: an Upstream from the pool or a fresh connect."""
    if pool is not None:
        sock = pool.get(host, port)
        if sock is not None:
            return Upstream(sock, host, port)
    sock = yield from dial((host, port), deadline, edge="connect")
    return Upstream(sock, host, port)


# ---------------------------------------------------------------------------
# the server shell
# ---------------------------------------------------------------------------

class EventLoopServer:
    """Drop-in replacement for the router's ThreadingHTTPServer surface:
    ``server_address`` / ``serve_forever()`` / ``shutdown()`` /
    ``server_close()`` — but one selectors loop instead of a thread per
    connection.

    ``conn_handler(server, sock, addr)`` returns the per-connection
    coroutine.  ``gate(server)`` runs at accept time BEFORE any
    connection state is allocated: returning a reason string sheds the
    connection (``shed_response`` is written best-effort, ``on_shed``
    counts it) — the ``--max-conns`` admission control and the
    ``conn_accept`` fault seam both live in the router's gate."""

    def __init__(self, address, conn_handler, gate=None,
                 shed_response: bytes = b"", on_shed=None,
                 backlog: int = 1024):
        self._handler = conn_handler
        self._gate = gate
        self._shed_response = shed_response
        self._on_shed = on_shed
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(backlog)
        self._sock.setblocking(False)
        self.server_address = self._sock.getsockname()
        self.loop = Loop()
        self.open_conns = 0  # loop-thread only (gauge reads tolerate tears)
        self._started = threading.Event()
        self._done = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self) -> None:
        self._started.set()
        try:
            self.loop.spawn(self._acceptor())
            self.loop.run()
        finally:
            _close_quiet(self._sock)
            self._done.set()

    def shutdown(self) -> None:
        """Stop the loop from any thread; waits for serve_forever to
        return (in-flight connection tasks are closed, their finally
        blocks shut their sockets)."""
        self.loop.call_threadsafe(self.loop.stop)
        if self._started.is_set():
            self._done.wait(timeout=10.0)

    def server_close(self) -> None:
        _close_quiet(self._sock)

    # -- accept path -------------------------------------------------------

    def _shed(self, sock, reason: str) -> None:
        """Refuse one connection before allocating state: best-effort
        canned response (it fits any socket buffer), close, count."""
        if self._on_shed is not None:
            self._on_shed(reason)
        try:
            sock.send(self._shed_response)
        except OSError:
            pass  # the shed client gets a bare close instead — still shed
        _close_quiet(sock)

    @loop_callback
    def _acceptor(self):
        while True:
            yield _Wait(self._sock.fileno(), selectors.EVENT_READ, None,
                        "accept")
            while True:
                pair = _accept_nb(self._sock)
                if pair is None:
                    break
                sock, addr = pair
                sock.setblocking(False)
                try:
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass  # non-TCP test sockets — latency hint only
                reason = self._gate(self) if self._gate is not None else None
                if reason:
                    self._shed(sock, reason)
                    continue
                self.open_conns += 1
                self.loop.spawn(self._conn_task(sock, addr))

    @loop_callback
    def _conn_task(self, sock, addr):
        try:
            yield from self._handler(self, sock, addr)
        finally:
            self.open_conns -= 1
            _close_quiet(sock)
