"""OpenAI-compatible HTTP API server.

TPU-native counterpart of the reference `dllama-api` app
(`/root/reference/src/apps/dllama-api/dllama-api.cpp`):

* ``POST /v1/chat/completions`` — messages + ``temperature`` / ``top_p`` /
  ``seed`` / ``max_tokens`` / ``stop`` / ``stream`` (SSE ``data:`` chunks
  terminated by ``[DONE]``), matching the reference's handled params
  (`dllama-api.cpp:202-314`).
* ``GET /v1/models`` — the single loaded model (`dllama-api.cpp:316-322`).

Design differences, all deliberate:

* Requests are parsed by the stdlib ``http.server`` with proper
  Content-Length framing — the reference's single-``recv`` parse can truncate
  large bodies (`/root/reference/src/socket.cpp:309-339`, a SURVEY.md §7
  quirk we do not replicate).
* Per-request sampler settings are *traced* arguments of the jitted decode
  step (see runtime.sampler.sample_dynamic), so every request shares one
  compiled program regardless of its temperature/top_p/seed.
* Stop sequences use an incremental detector that withholds only the bytes
  that could still begin a stop string, instead of re-scanning the last 8
  pieces every token (`dllama-api.cpp:264-299`).

Like the reference, one request is served at a time (the engine owns one KV
cache); concurrent connections queue on a lock rather than corrupting state.
"""

from __future__ import annotations

import base64
import codecs
import itertools
import json
import os
import queue as queue_mod
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dllama_tpu import faults, observability
from dllama_tpu.analysis.sanitize import guarded_by
from dllama_tpu.observability import RequestTrace
from dllama_tpu.obsv import BurnRateEngine, Sampler, TimeSeriesStore
from dllama_tpu.obsv.timeseries import parse_window
from dllama_tpu.runtime.generate import NumericHealthError
from dllama_tpu.runtime.sampler import SamplerConfig
from dllama_tpu.serving import kv_transfer
from dllama_tpu.serving.lifecycle import (
    AdmissionGate,
    CancelToken,
    Deadline,
    DeadlineExceeded,
    KVBudget,
    LifecycleError,
    SchedulerCrashed,
    SLO_CLASSES,
    Supervisor,
    parse_slo_classes,
)
from dllama_tpu.serving.protocol import (HDR_CKPT, HDR_CKPT_WIRE, HDR_CLASS,
                                         HDR_PARENT_SPAN, HDR_REQUEST_ID,
                                         HDR_RESUME_OFFSET,
                                         HDR_SERVER_TIMING, SSE_EVENT_CKPT)
from dllama_tpu.serving.templates import render_llama2_turn, render_llama3_chat

#: the checkpoint control frame's prefix, derived from the registered event
#: name so emitter and scanner can never drift
_SSE_CKPT_PREFIX = b"event: " + SSE_EVENT_CKPT.encode() + b"\ndata: "


class StopDetector:
    """Incremental stop-string scanner for a streamed byte flow.

    ``feed`` returns (text_safe_to_emit, stopped). Bytes that could be the
    start of a stop sequence are withheld until disambiguated, so a stop
    string spanning two tokens is still caught and never leaks downstream.
    """

    def __init__(self, stops: list):
        self.stops = [s for s in stops if s]
        self.hold = ""  # tail that may be a stop-string prefix
        self.stopped = False

    def _partial_len(self, text: str) -> int:
        """Length of the longest tail of ``text`` that prefixes any stop."""
        best = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(text)), 0, -1):
                if s.startswith(text[-k:]):
                    best = max(best, k)
                    break
        return best

    def feed(self, piece: str) -> tuple:
        if self.stopped:
            return "", True
        text = self.hold + piece
        # earliest occurrence across ALL stops wins (OpenAI semantics), not
        # first stop in list order
        hits = [i for i in (text.find(s) for s in self.stops) if i != -1]
        if hits:
            self.stopped = True
            self.hold = ""
            return text[: min(hits)], True
        k = self._partial_len(text)
        self.hold = text[-k:] if k else ""
        return text[: len(text) - k], False

    def flush(self) -> str:
        out, self.hold = self.hold, ""
        return out

    def state(self) -> dict:
        """Scanback state for the kv_transfer v2 header — what a
        stop-string session must carry to migrate/resume without leaking
        (or double-emitting) a held stop-prefix tail."""
        return {"stops": list(self.stops), "hold": self.hold,
                "stopped": self.stopped}

    @classmethod
    def from_state(cls, state: dict) -> "StopDetector":
        d = cls([str(s) for s in state.get("stops", [])])
        d.hold = str(state.get("hold", ""))
        d.stopped = bool(state.get("stopped", False))
        return d


def padded_batch(prompts: list, row_steps: list) -> tuple:
    """Pad a prompt batch to the next power of two with dummy [0] rows of
    budget 1 (dropped by the caller): distinct request counts reuse a
    handful of compiled batch programs instead of one XLA compile per B."""
    b = 1 << (len(prompts) - 1).bit_length()
    pad = b - len(prompts)
    return prompts + [[0]] * pad, row_steps + [1] * pad


def decode_token_row(tok, prev: int, row: list, stop_ids: tuple,
                     stops: list) -> tuple:
    """Token ids -> (text, finish_reason, tokens_consumed) with the same
    stop-token / stop-string / dangling-UTF-8 semantics as the streaming
    loop. Shared by every batched response path (GreedyBatcher, `n`)."""
    detector = StopDetector(stops)
    utf8 = codecs.getincrementaldecoder("utf-8")("replace")
    text_parts: list = []
    finish, n_gen = "length", 0
    for t in row:
        n_gen += 1
        if t in stop_ids:
            finish = "stop"
            break
        piece = utf8.decode(tok.decode_piece(prev, t))
        prev = t
        out, hit = detector.feed(piece)
        if out:
            text_parts.append(out)
        if hit:
            finish = "stop"
            break
    if not detector.stopped:
        tail = detector.flush() + utf8.decode(b"", True)
        if tail:
            text_parts.append(tail)
    return "".join(text_parts), finish, n_gen


@guarded_by("_lock", "_supervisor", "_window", "_active_sess", "_keep_sess",
            "_class_stats")
class Batcher:
    """CONTINUOUS batching scheduler: concurrent completions — greedy AND
    sampled, non-streaming AND streaming — share one resident slot-pool
    decode (``Engine.batch_session``). A dedicated scheduler thread drains
    an arrival queue and admits requests into free cache slots BETWEEN fused
    decode chunks, so a request arriving mid-decode starts after at most one
    chunk (~chunk tokens) instead of waiting for the whole running batch to
    drain, and a finished row's slot is handed to the next waiter the moment
    it stops — the static-window pathology (a long row holding K idle slots
    hostage) is gone. Every row runs its own sampler chain (per-row
    temperature/topp/seed are traced arrays), so greedy rows AND sampled
    rows are bit-identical to their solo runs with the same SamplerConfig —
    WHENEVER they were admitted. The reference serves strictly one request
    at a time (`/root/reference/src/apps/dllama-api/dllama-api.cpp:324-355`).

    Streaming rows consume a per-slot queue fed from the scheduler loop:
    tokens arrive in fused-chunk bursts (``--batch-chunk`` tokens per
    dispatch) rather than one SSE event per token — the granularity cost of
    sharing one device program across the pool.

    Two special cases keep their faster paths: a batch of ONE delegates to
    the solo engine path (prefix-session KV reuse, per-token streaming —
    _serve_solo), and an all-greedy window on a --spec-draft server runs the
    batched speculative verify (_serve_spec) when it fits the pool at once —
    speculation's drafting arithmetic assumes a fixed row set, so it runs
    run-to-completion; overflow and mixed windows take the continuous path.

    KV-reuse trade, explicitly: pooled rows (>= 2 concurrent) neither claim
    nor store prefix sessions (extracting per-row sessions from the pool
    cache would pin max_batch full-context KV caches in HBM — the session
    cache's budget is ~2). So under SUSTAINED concurrency a multi-turn chat
    re-prefills its history each turn; that is the deliberate price for
    sharing every decode weight stream, and prefill is the cheap
    (MXU-bound, bucketed) phase. The zero/low-concurrency cases keep full
    reuse: prompts extending a cached session route solo at the gate, and
    singletons delegate to _serve_solo.
    """

    class _Slot:
        __slots__ = ("prompt", "steps", "sampler", "tokens", "error", "done",
                     "queue", "deadline", "cancel", "trace", "kind", "snap",
                     "export", "ckpt_every", "since_ckpt", "slo_class",
                     "preempted")

        def __init__(self, prompt, steps, sampler, streaming: bool,
                     deadline=None, cancel=None, trace=None,
                     kind: str = "completion", snap=None,
                     ckpt_every: int = 0, slo_class: str = "interactive"):
            self.prompt, self.steps, self.sampler = prompt, steps, sampler
            self.tokens = None
            self.error = None
            self.done = threading.Event()
            #: SLO lane ("interactive"/"batch"): drives lane ordering at
            #: admission and marks batch rows preemptible
            self.slo_class = slo_class
            #: True while this row sits in the scheduler's preempted
            #: parking lot (exported at a chunk boundary to make room for
            #: interactive work; re-admitted via admit_from_export)
            self.preempted = False
            #: disaggregation job kind: "completion" (the normal request),
            #: "prefill" (admit + first chunk, then export the row's KV
            #: pages for migration) or "import" (admit a row warm from a
            #: sibling replica's export snapshot and continue its decode)
            self.kind = kind
            #: decoded kv_transfer snapshot (kind "import" only)
            self.snap = snap
            #: export_row snapshot (kind "prefill", when the row migrated
            #: instead of finishing inside its first chunk)
            self.export = None
            #: mid-stream failover: checkpoint this streaming row every N
            #: emitted tokens (0 = off). Token-count based, so the ckpt
            #: schedule is deterministic across identical greedy runs.
            self.ckpt_every = int(ckpt_every) if streaming else 0
            self.since_ckpt = 0
            # streaming protocol: list-of-token-ids items, then exactly one
            # terminal item — None (clean end) or an Exception
            self.queue = queue_mod.Queue() if streaming else None
            #: lifecycle.Deadline — wall-clock budget from submit, checked
            #: by the scheduler BETWEEN chunks (and between solo tokens)
            self.deadline = deadline
            #: lifecycle.CancelToken — set by the SSE writer when the client
            #: socket dies; the scheduler releases the row's slot at the
            #: next chunk boundary instead of decoding for a dead socket
            self.cancel = cancel
            #: observability.RequestTrace — the scheduler marks routing
            #: (mark_start: which path served this request), prefill and
            #: token times on it; the HTTP handler owns emission
            self.trace = trace

        def mark_start(self, path: str) -> None:
            if self.trace is not None:
                self.trace.mark_start(path)

        def mark_prefill(self, ms: float) -> None:
            if self.trace is not None:
                self.trace.mark_prefill(ms)

        def mark_prefill_chunk(self, t_begin: float, t_end: float) -> None:
            if self.trace is not None:
                self.trace.mark_prefill_chunk(t_begin, t_end)

        def mark_token(self) -> None:
            if self.trace is not None:
                self.trace.mark_token()

        def lifecycle_error(self):
            """None, or the typed error that should resolve this request
            NOW (cancellation outranks deadline: a dead client's row frees
            its slot whatever its remaining budget)."""
            if self.cancel is not None and self.cancel.cancelled:
                return self.cancel.error()
            if self.deadline is not None and self.deadline.expired():
                return self.deadline.error()
            return None

    #: extra client-side wait past a slot's deadline before the HTTP thread
    #: gives up on the scheduler resolving it (a wedged device dispatch must
    #: not hang the connection forever — the chaos suite's no-hang bound)
    DEADLINE_GRACE_S = 5.0

    def __init__(self, state, window_ms: float = 15.0, max_batch: int = 8,
                 chunk: int = 8, prefill_chunk: int = -1,
                 kv_buckets: bool = True, kv_bucket_min: int = 0,
                 kv_pages: int = 0, slo_classes: dict = None):
        self.state = state
        #: {name: lifecycle.SLOClass} — per-lane admission order and
        #: residency caps (see _serve_continuous's lane-aware admission)
        self.slo_classes = (slo_classes if slo_classes is not None
                            else parse_slo_classes(""))
        self.window_s = window_ms / 1000.0
        #: HBM bound: the pool's KV budget is max_batch full-context caches
        #: (--batch-max; size against seq_len x n_layers x kv x cache dtype)
        self.max_batch = max(1, max_batch)
        #: fused steps between admission checks (--batch-chunk): smaller =
        #: lower admission latency for mid-decode arrivals, larger = fewer
        #: host round trips per token
        self.chunk = max(1, chunk)
        #: --prefill-chunk: prompt tokens consumed per scheduler tick while
        #: a long prompt fills its cache (admit_begin/prefill_step).
        #: < 0 = auto (one decode chunk's worth of token-forwards:
        #: chunk * max_batch); 0 = monolithic admission (the pre-chunking
        #: behavior: every resident row stalls for the whole prefill)
        self.prefill_chunk = (self.chunk * self.max_batch
                              if prefill_chunk < 0 else int(prefill_chunk))
        #: --kv-buckets: length-bucketed slot pools under the same modeled
        #: HBM budget (more resident rows for short traffic); off = the
        #: classic uniform [L, max_batch, S, kv, hd] slab
        self.kv_buckets = bool(kv_buckets)
        self.kv_bucket_min = max(0, int(kv_bucket_min))
        #: --kv-pages: paged KV pool + radix prefix cache (tokens per page;
        #: 0 = slab modes). Shared prompt prefixes are aliased
        #: copy-on-write instead of re-prefilled, under the same budget
        self.kv_pages = max(0, int(kv_pages))
        #: serving-side KV accountant, shared across pool sessions so the
        #: dllama_kv_* gauges stay continuous between traffic bursts
        self.kv_budget = KVBudget(
            self.max_batch * int(getattr(state.cfg, "seq_len", 1)))
        self._lock = threading.Lock()
        self._arrivals: queue_mod.Queue = queue_mod.Queue()
        # scheduler-layer telemetry (shares the server's registry): which
        # path served each request, and how full the slot pool ran
        reg = state.metrics
        self._m_path = reg.counter(
            "dllama_requests_path_total",
            "Completions served, by decode path (solo/spec/continuous)",
            ("path",))
        self._m_occupancy = reg.histogram(
            "dllama_batch_occupancy",
            "Occupied slots of the pooled decode session, observed per "
            "fused chunk",
            buckets=tuple(float(i) for i in range(1, self.max_batch + 1)))
        # SLO-class scheduling telemetry: every preemption decision by
        # outcome, plus live per-lane pressure (these two gauges are what
        # `cli top`'s lane columns read off /metrics/fleet)
        self._m_preemptions = reg.counter(
            "dllama_preemptions_total",
            "Chunk-boundary preemptions of batch-class rows, by outcome "
            "(ok=exported+parked, resumed=re-admitted bit-identically, "
            "retry=re-admission deferred, injected/error=preemption "
            "aborted, row kept decoding)",
            ("outcome",))
        self._m_class_queue = reg.gauge(
            "dllama_class_queue_depth",
            "Requests waiting for a decode slot, by SLO class",
            ("slo_class",))
        self._m_class_resident = reg.gauge(
            "dllama_class_resident_rows",
            "Rows resident in the decode slot pool, by SLO class",
            ("slo_class",))
        self._m_class_preempted = reg.gauge(
            "dllama_class_preempted_rows",
            "Preempted rows parked awaiting re-admission, by SLO class",
            ("slo_class",))
        #: latest per-lane scheduler snapshot ({class: {waiting, resident,
        #: preempted}}), published each chunk tick for /ready (the router's
        #: class-aware scoring reads it there)
        self._class_stats = {name: {"waiting": 0, "resident": 0,
                                    "preempted": 0}
                             for name in SLO_CLASSES}
        #: lifecycle.Supervisor owning the scheduler thread: a crashed loop
        #: fails its window's slots 503 and restarts instead of leaving
        #: every later submit() hanging on a dead daemon
        self._supervisor: Supervisor = None
        #: the window currently being routed — what _on_crash must fail
        self._window: list = []
        #: the live slot-pool session (while _serve_continuous runs):
        #: readiness reporting + crash cleanup
        self._active_sess = None
        #: paged mode keeps ONE session resident across batch windows: the
        #: arena IS the radix prefix cache, so closing it per window would
        #: throw away every cached system prompt. Slab modes still open and
        #: close per window (idle HBM freed); closed on crash cleanup.
        self._keep_sess = None

    # -- introspection (readiness probe) ----------------------------------
    @property
    def scheduler_alive(self) -> bool:
        """False only when the scheduler thread has died and the supervisor
        has not (yet) restarted it; a never-started scheduler is healthy —
        it starts on demand at the first submit."""
        sup = self._supervisor
        return sup is None or sup.alive

    @property
    def crash_count(self) -> int:
        sup = self._supervisor
        return 0 if sup is None else sup.crash_count

    def queue_depth(self) -> int:
        """Arrivals waiting for the scheduler to route them."""
        return self._arrivals.qsize()

    def occupancy(self) -> tuple:
        """(occupied slots, pool size) of the live decode session — (0, B)
        between pool sessions."""
        sess = self._active_sess
        return (len(sess.occupied) if sess is not None else 0, self.max_batch)

    def kv_info(self) -> dict:
        """KV occupancy for /ready and /stats: token reservations, resident
        rows per bucket (slab modes) and — in paged mode — page-pool state
        plus the prefix-cache hit rate. The multi-replica router weighs
        replicas by exactly this payload, so the load-picture fields it
        scores on (``kv_pages_free``/``kv_pages_total``/``prefix_hit_rate``)
        are ALWAYS present — zero in slab modes — and one cheap /ready
        probe carries the whole picture (/stats stays a superset)."""
        info = {
            "kv_tokens_reserved": self.kv_budget.reserved,
            "kv_tokens_budget": self.kv_budget.total_tokens,
            "kv_rows": {str(k): v for k, v in sorted(
                self.kv_budget.rows_by_bucket().items()) if v},
            "kv_pages_free": 0,
            "kv_pages_total": 0,
            "prefix_hit_rate": 0.0,
        }
        if self.kv_pages > 0:
            sess = self._active_sess or self._keep_sess
            pages = (sess.page_stats() if sess is not None
                     else self.kv_budget.page_stats())
            info["kv_pages"] = pages
            info["kv_pages_free"] = pages.get("pages_free", 0)
            info["kv_pages_total"] = pages.get("pages_total", 0)
            info["prefix_hit_rate"] = pages.get("prefix_hit_rate", 0.0)
        return info

    def class_stats(self) -> dict:
        """Per-SLO-class lane pressure ({class: {waiting, resident,
        preempted}}) as of the last scheduler tick — the readiness probe's
        lane view (the router scores classes off this)."""
        with self._lock:
            return {k: dict(v) for k, v in self._class_stats.items()}

    def _publish_class_stats(self, waiting: list, slot_map: dict,
                             preempted: list) -> None:
        """One chunk tick's lane picture -> gauges + readiness snapshot."""
        stats = {name: {"waiting": 0, "resident": 0, "preempted": 0}
                 for name in SLO_CLASSES}
        for s in waiting:
            if s.slo_class in stats:
                stats[s.slo_class]["waiting"] += 1
        for s in slot_map.values():
            if s.slo_class in stats:
                stats[s.slo_class]["resident"] += 1
        for s in preempted:
            if s.slo_class in stats:
                stats[s.slo_class]["preempted"] += 1
        for name, row in stats.items():
            self._m_class_queue.set(row["waiting"], slo_class=name)
            self._m_class_resident.set(row["resident"], slo_class=name)
            self._m_class_preempted.set(row["preempted"], slo_class=name)
        with self._lock:
            self._class_stats = stats

    def _class_resident_cap(self, slo_class: str) -> int:
        """The lane's max resident decode rows (0 = unbounded)."""
        cls = self.slo_classes.get(slo_class)
        return max(0, cls.max_resident) if cls is not None else 0

    def _preempt_one(self, sess, slot_map: dict, preempted: list) -> bool:
        """Preempt ONE batch-class resident row at this chunk boundary to
        make room for queued interactive work: snapshot its KV pages +
        sampler chain with the failover export machinery, free its slot,
        and park the SAME slot (queue and all — its SSE stream just pauses)
        for bit-identical re-admission via admit_from_export once pressure
        drops. A faulted/failed export leaves the row decoding untouched —
        preemption must never tear a healthy stream."""
        mid_prefill = set(sess.pending_prefills)
        victims = [b for b, s in slot_map.items()
                   if s.slo_class == "batch" and s.kind != "prefill"
                   and b not in mid_prefill  # a half-built cache has no
                   #  resumable snapshot — it waits out its prefill
                   and not sess.is_done(b)
                   and s.lifecycle_error() is None]
        if not victims:
            return False
        b = victims[-1]  # youngest batch row: least decode work discarded
        s = slot_map[b]
        try:
            faults.fire("preempt")
            snap = sess.export_row(b, fire_fault=False)
        except faults.FaultInjected:
            self._m_preemptions.inc(outcome="injected")
            return False
        except Exception:  # noqa: BLE001 — mid-prefill/unexportable row
            self._m_preemptions.inc(outcome="error")
            return False
        self._m_preemptions.inc(outcome="ok")
        self.state.flight.record(
            "preempt", request_id=(s.trace.request_id
                                   if s.trace is not None else None),
            emitted=int(snap.get("emitted", 0)))
        sess.release(b)
        del slot_map[b]
        s.kind = "import"
        s.snap = snap
        s.preempted = True
        preempted.append(s)
        return True

    def _serve_solo(self, s) -> None:
        """A batch of ONE delegates to the solo engine path, WITH prefix-
        session claim/store: a lone conversation ticking along under
        --batch-window must keep its KV reuse (and per-token streaming
        granularity) instead of re-prefilling its whole history through the
        batch path every turn — batching only changes anything under real
        concurrency. Caller holds state.lock. Tokens are bit-identical to
        the batched row (same per-request chain; the invariant
        generate_batch documents). A --spec-draft server speculates here
        too (generate_spec is exact at any temperature)."""
        st = self.state
        try:
            err = s.lifecycle_error()
            if err is not None:
                self._resolve_err(s, err)
                return
            s.mark_start("solo")
            self._m_path.inc(path="solo")
            session, feed = st.take_prefix_session(s.prompt)
            history = list(s.prompt)
            stream = st.open_stream(s.prompt, feed, session, s.steps,
                                    s.sampler)
            toks: list = []
            err = None
            for t, _ in stream:
                history.append(t)
                toks.append(t)
                s.mark_token()
                if s.queue is not None:
                    s.queue.put([t])
                err = s.lifecycle_error()
                if err is not None:
                    break  # abandon the generator at a token boundary;
                    # final_session is refreshed before every yield, so the
                    # stored state matches exactly what was consumed
            s.mark_prefill(getattr(st.engine, "prefill_ms", 0.0) or 0.0)
            st.store_prefix_session(history, st.engine.final_session)
            if err is not None:
                self._resolve_err(s, err)
                return
            s.tokens = toks
            if s.queue is not None:
                s.queue.put(None)
            s.done.set()
        except Exception as e:  # noqa: BLE001
            self._resolve_err(
                s, e if isinstance(e, (LifecycleError, NumericHealthError))
                else RuntimeError(f"decode failed: {e!r}"))

    @staticmethod
    def _resolve_err(s, err) -> None:
        """Resolve ONE waiter with ``err`` (typed lifecycle errors pass
        through so the handler can speak their HTTP status)."""
        s.error = err
        if s.queue is not None:
            s.queue.put(err)
        s.done.set()

    def _fail(self, slots, e) -> None:
        """Resolve every waiter with an error — ALWAYS on failure (a waiter
        left hanging would hang its HTTP connection)."""
        err = (e if isinstance(e, (LifecycleError, NumericHealthError))
               else RuntimeError(f"batched decode failed: {e!r}"))
        for s in slots:
            self._resolve_err(s, err)

    def _serve_spec(self, batch: list) -> None:
        """All-greedy window on a --spec-draft server: BATCHED speculative
        verify — every launch scores draft_len+1 positions for all rows
        (exact; rows equal plain batched greedy), single-device or
        quantized-TP. Streaming rows get per-launch bursts (already
        budget/stop-truncated). Run-to-completion: speculation's per-row
        drafting state assumes a fixed row set, so this fast path keeps the
        static shape — the scheduler only routes a window here when it fits
        the pool at once; contended windows decode continuously instead.
        The prompt list is padded to the next power of two (dummy greedy
        [0] rows of budget 1, dropped after) so distinct arrival counts
        reuse a handful of compiled batch sizes.

        Lifecycle: cancelled/expired requests are resolved BEFORE the batch
        forms, AND mid-verify via ``row_cancel``: between verify launches a
        row whose client died (or whose deadline expired) stops decoding —
        the fixed row set speculation needs is preserved (the cancelled row
        keeps its slot but spends no more launches on new tokens), and its
        waiter is resolved with the typed error right after the batch."""
        batch = [s for s in batch if not self._reap_slot(s)]
        if not batch:
            return
        try:
            for s in batch:
                s.mark_start("spec")
                self._m_path.inc(path="spec")
            prompts, row_steps = padded_batch(
                [s.prompt for s in batch], [s.steps for s in batch])

            def on_step(fresh):
                for i, s in enumerate(batch):
                    if fresh[i]:
                        s.mark_token()
                        if s.queue is not None:
                            s.queue.put(fresh[i])

            def row_cancel(i):
                return (i < len(batch)
                        and batch[i].lifecycle_error() is not None)

            # explicit greedy sampler: the ENGINE default may be sampled
            # (CLI --temperature 0.8) and would trip the greedy-only
            # guard even though every REQUEST in this batch is greedy
            rows, _stats = self.state.engine.generate_batch_spec(
                prompts, max(s.steps for s in batch),
                stop_tokens=self.state.stop_token_ids(),
                row_steps=row_steps,
                draft_len=self.state.spec_draft,
                sampler=SamplerConfig(temperature=0.0, seed=0),
                on_step=on_step,
                row_cancel=row_cancel,
            )
            prefill_ms = getattr(self.state.engine, "prefill_ms", 0.0) or 0.0
            for s, row in zip(batch, rows):
                s.mark_prefill(prefill_ms)
                if self._reap_slot(s):
                    continue  # cancelled/expired mid-verify: typed error
                s.tokens = row[: s.steps]
                if s.queue is not None:
                    s.queue.put(None)
                s.done.set()
        except Exception as e:  # noqa: BLE001 — every waiter gets a 500
            self._fail(batch, e)

    def _reap_slot(self, s) -> bool:
        """Resolve ``s`` with its lifecycle error if it has one. True when
        the slot was resolved (drop it from scheduling)."""
        err = s.lifecycle_error()
        if err is None:
            return False
        self._resolve_err(s, err)
        return True

    def _serve_continuous(self, batch: list) -> None:
        """THE continuous path: open a slot-pool session, admit ``batch``
        into free slots, and between every fused chunk (a) stream each live
        row's fresh burst to its own queue, (b) release rows the moment
        they hit stop/budget — resolving their waiters immediately, not at
        batch end — and (c) admit newly arrived requests into the freed
        slots (rolling admission; the arrival queue is polled between
        chunks, so a mid-decode arrival waits at most one chunk). Runs
        until the pool drains AND no arrivals are waiting. Every admitted
        row is bit-identical to its solo run (BatchSession's invariant);
        the session is closed on exit so the pool cache's HBM is held only
        while traffic needs it."""
        st = self.state
        stop_ids = st.stop_token_ids()
        waiting = list(batch)
        slot_map: dict = {}  # session slot handle -> _Slot
        #: batch-class rows exported out of the pool to make room for
        #: interactive work; re-admitted (bit-identically) once no
        #: interactive request is waiting. Scheduler-thread-local, like
        #: ``waiting`` — readiness reads the _publish_class_stats snapshot.
        preempted: list = []
        sess = None
        try:
            sess = self._keep_sess
            if sess is None:
                sess = st.engine.batch_session(
                    self.max_batch, chunk=self.chunk,
                    bucket_kv=self.kv_buckets,
                    min_bucket=self.kv_bucket_min or None,
                    prefill_chunk=self.prefill_chunk,
                    kv_budget=self.kv_budget,
                    kv_pages=self.kv_pages)
                if self.kv_pages > 0:
                    with self._lock:
                        self._keep_sess = sess
            with self._lock:
                self._active_sess = sess
            while waiting or slot_map or preempted:
                # lifecycle reap, BETWEEN chunks: a cancelled (client gone)
                # or deadline-expired row is released NOW — its slab goes to
                # the next waiter this very loop pass — and dead waiters
                # never occupy a slot at all (a mid-prefill row's half-built
                # cache is dropped the same way). Parked preempted rows reap
                # identically: a batch client that gave up while parked
                # resolves here instead of being pointlessly re-admitted.
                waiting = [s for s in waiting if not self._reap_slot(s)]
                preempted = [s for s in preempted if not self._reap_slot(s)]
                # pressure dropped (no interactive work queued): move every
                # parked batch row back to the FRONT of the line — resumed
                # work outranks new batch arrivals (it already paid for its
                # decoded prefix once)
                if preempted and not any(s.slo_class == "interactive"
                                         for s in waiting):
                    waiting = preempted + waiting
                    preempted = []
                for b in list(slot_map):
                    s = slot_map[b]
                    err = s.lifecycle_error()
                    if err is not None:
                        sess.cancel(b)
                        sess.release(b)
                        del slot_map[b]
                        self._resolve_err(s, err)
                # paged sessions get the actual tokens so admission counts
                # the radix prefix match (a warm prompt needs fewer pages)
                while waiting:
                    # per-class lanes: interactive admits first (FIFO
                    # within a lane); a batch waiter additionally honors
                    # its lane's max_resident cap. Import jobs (disagg
                    # migrations, preempted resumes) skip the cap — a
                    # migration refused residency would fail the transfer.
                    resident: dict = {}
                    for sl in slot_map.values():
                        resident[sl.slo_class] = \
                            resident.get(sl.slo_class, 0) + 1
                    pick = None
                    for lane in SLO_CLASSES:
                        cap = self._class_resident_cap(lane)
                        for i, w in enumerate(waiting):
                            if w.slo_class != lane:
                                continue
                            if (w.kind != "import" and cap
                                    and resident.get(lane, 0) >= cap):
                                break  # lane at its residency cap (FIFO
                                #        holds: no later same-lane waiter
                                #        may jump the capped head)
                            pick = i
                            break
                        if pick is not None:
                            break
                    if pick is None:
                        break  # every lane capped out this tick
                    s = waiting[pick]
                    if s.kind == "import":
                        # migrated row arriving: admit it warm from its
                        # export snapshot NOW — no can_admit wait (a full
                        # pool must fail fast so the router can fall back
                        # to re-prefilling, not queue behind cold prompts).
                        # A preempted row coming back rides the same path,
                        # but a failed RE-admission re-parks it (retry next
                        # tick) instead of failing the client.
                        waiting.pop(pick)
                        resumed = s.preempted
                        if not resumed:
                            s.mark_start("import")
                            self._m_path.inc(path="import")
                        try:
                            b = sess.admit_from_export(s.prompt, s.snap)
                        except Exception as e:  # noqa: BLE001 — this row
                            if resumed:
                                self._m_preemptions.inc(outcome="retry")
                                preempted.append(s)
                                break  # no room this tick; decode on
                            self.state._m_kv_imports.inc(outcome="error")
                            self._fail([s], e)
                            continue
                        if resumed:
                            s.preempted = False
                            self._m_preemptions.inc(outcome="resumed")
                        else:
                            self.state._m_kv_imports.inc(outcome="ok")
                            s.tokens = []
                        s.snap = None  # free the page payloads now
                        slot_map[b] = s
                        continue
                    if not sess.can_admit(len(s.prompt), s.steps, s.prompt):
                        # pool full for the highest-priority waiter: an
                        # interactive one reclaims batch residency at this
                        # very chunk boundary and retries immediately
                        if (s.slo_class == "interactive"
                                and self._preempt_one(sess, slot_map,
                                                      preempted)):
                            continue
                        break
                    waiting.pop(pick)
                    path = ("prefill" if s.kind == "prefill"
                            else "continuous")
                    s.mark_start(path)
                    self._m_path.inc(path=path)
                    pre_admit_ms = sess.prefill_ms
                    try:
                        if self.prefill_chunk > 0:
                            # chunked admission: reserve the row now, feed
                            # the prompt one prefill_step per tick below —
                            # resident rows keep decoding in between
                            b = sess.admit_begin(
                                s.prompt, s.steps, sampler=s.sampler,
                                stop_tokens=stop_ids)
                        else:
                            b = sess.admit(s.prompt, s.steps,
                                           sampler=s.sampler,
                                           stop_tokens=stop_ids)
                    except Exception as e:  # noqa: BLE001 — this row only
                        self._fail([s], e)
                        continue
                    if self.prefill_chunk <= 0:
                        s.mark_prefill(sess.prefill_ms - pre_admit_ms)
                    s.tokens = []
                    slot_map[b] = s
                # ONE incremental prefill piece per tick (FIFO): the oldest
                # pending prompt advances by <= prefill_chunk tokens, so
                # every resident row's inter-token gap is bounded by one
                # prefill chunk + one decode chunk instead of a whole
                # monolithic prompt
                if self.prefill_chunk > 0:
                    t_pf = time.monotonic()
                    adv = sess.prefill_step()
                    if adv is not None:
                        b, finished = adv
                        s = slot_map.get(b)
                        if s is not None:
                            s.mark_prefill_chunk(t_pf, time.monotonic())
                            if finished:
                                s.mark_prefill(sess.prefill_ms_of(b))
                self._publish_class_stats(waiting, slot_map, preempted)
                if slot_map:
                    self._m_occupancy.observe(float(len(slot_map)))
                    # the black box keeps the in-flight request ids per
                    # tick: a replica killed mid-decode dumps a ring whose
                    # last events say exactly whose work died with it
                    st.flight.record(
                        "chunk_tick", rows=len(slot_map),
                        requests=[s.trace.request_id
                                  for s in slot_map.values()
                                  if s.trace is not None][:8])
                for b, burst in sess.step_chunk().items():
                    s = slot_map[b]
                    s.tokens.extend(burst)
                    if burst:
                        s.mark_token()
                    if s.queue is not None and burst:
                        s.queue.put(burst)
                    if sess.is_done(b):
                        # free the slab NOW — the next waiter admits into
                        # it on this very loop pass
                        quarantined = sess.finish_reason(b) == "error"
                        sess.release(b)
                        del slot_map[b]
                        if quarantined:
                            # numeric-health quarantine: THIS row's logits
                            # went non-finite; its waiter gets the typed
                            # error (500 / finish_reason "error"), siblings
                            # decode on bit-identically
                            self._resolve_err(s, NumericHealthError(
                                "in pooled decode row; row quarantined"))
                            continue
                        if s.queue is not None:
                            s.queue.put(None)
                        s.done.set()
                    elif s.kind == "prefill":
                        # first chunk after go-live and the row is NOT done:
                        # migrate now — snapshot its pages + decode state,
                        # free the slot, and hand the snapshot (plus the
                        # chunk's already-emitted tokens) to the exporting
                        # HTTP handler. A faulted/failed export frees the
                        # slot the same way and fails THIS waiter only.
                        try:
                            snap = sess.export_row(b)
                        except Exception as e:  # noqa: BLE001
                            self.state._m_kv_exports.inc(outcome="error")
                            sess.cancel(b)
                            sess.release(b)
                            del slot_map[b]
                            self._fail([s], e)
                            continue
                        self.state._m_kv_exports.inc(outcome="ok")
                        sess.release(b)
                        del slot_map[b]
                        s.export = snap
                        s.done.set()
                    elif s.ckpt_every > 0 and s.queue is not None and burst:
                        # mid-stream failover checkpoint, taken AT the
                        # chunk boundary (so it lines up with an SSE event
                        # boundary downstream) and pushed THROUGH the
                        # queue: the writer attaches its rendering state
                        # at exactly the point the snapshot describes. The
                        # row stays live — a failed write is a skipped
                        # checkpoint (shorter resume coverage), never a
                        # stream error.
                        s.since_ckpt += len(burst)
                        if s.since_ckpt >= s.ckpt_every:
                            s.since_ckpt = 0
                            try:
                                faults.fire("ckpt_write")
                                snap = sess.export_row(b, fire_fault=False)
                            except Exception:  # noqa: BLE001
                                self.state._m_ckpt_writes.inc(
                                    outcome="error")
                            else:
                                self.state._m_ckpt_writes.inc(outcome="ok")
                                s.queue.put(("ckpt", snap))
                while True:  # rolling admission: drain mid-chunk arrivals
                    try:
                        waiting.append(self._arrivals.get_nowait())
                    except queue_mod.Empty:
                        break
        except Exception as e:  # noqa: BLE001 — every waiter gets a 500
            self._fail(list(slot_map.values()) + waiting + preempted, e)
            # a session that threw mid-window is suspect: never keep it
            if sess is not None and sess is self._keep_sess:
                with self._lock:
                    self._keep_sess = None
        finally:
            self._publish_class_stats([], {}, [])
            with self._lock:
                self._active_sess = None
            if sess is not None and sess is not self._keep_sess:
                sess.close()

    def _scheduler_loop(self) -> None:
        """The scheduler daemon: wait for an arrival, hold the admission
        window open for companions, then route the window — singleton ->
        solo path (prefix-cache reuse), all-greedy spec-capable fit ->
        batched speculative verify, anything else -> continuous slot-pool
        decode. The engine lock is held per window, so handler-side solo
        requests (stop strings, prefix-session extensions) interleave
        between windows exactly as before.

        Runs under a lifecycle.Supervisor: an exception escaping a window
        fails that window's slots with a 503-able SchedulerCrashed (see
        _on_crash) and the loop restarts — queued arrivals stay queued for
        the restarted thread. Returns (ending supervision) only when the
        server is draining and the queue is empty."""
        while True:
            try:
                first = self._arrivals.get(timeout=0.25)
            except queue_mod.Empty:
                if self.state.gate.draining:
                    return  # drain complete: clean supervisor exit
                continue
            if self.window_s > 0:
                time.sleep(self.window_s)  # let concurrent requests join
            window = [first]
            while True:
                try:
                    window.append(self._arrivals.get_nowait())
                except queue_mod.Empty:
                    break
            # NO try/finally here: on an exception _window must SURVIVE the
            # unwind so the supervisor's _on_crash can fail exactly these
            # slots (a finally would clear it first and strand the waiters)
            with self._lock:
                self._window = window
            faults.fire("scheduler")
            window = [s for s in window if not self._reap_slot(s)]
            if window:
                t_win = time.monotonic()
                # disaggregation jobs (prefill-export / import-admit) and
                # checkpointing streams exist only in the paged slot pool:
                # they never route solo or spec. Batch-class rows route
                # continuous too — solo/spec run-to-completion would make
                # them unpreemptible, and preemptibility is the lane's
                # contract
                plain = all(s.kind == "completion" and not s.ckpt_every
                            and s.slo_class == "interactive"
                            for s in window)
                with self.state.lock:  # the engine serves one pool at a time
                    if plain and len(window) == 1 and self._arrivals.empty():
                        self._serve_solo(window[0])
                    elif (plain and len(window) <= self.max_batch
                            and self.state.spec_draft > 0
                            and getattr(self.state.engine,
                                        "supports_batch_spec", False)
                            and all(s.sampler.temperature == 0.0
                                    for s in window)):
                        self._serve_spec(window)
                    else:
                        self._serve_continuous(window)
                # one span per routed window on the scheduler track (tid 0);
                # request tracks (allocated span ids) group right under it
                observability.emit_trace_events([
                    observability.scheduler_trace_event(
                        "scheduler_window", t_win, time.monotonic(),
                        {"window": len(window)})])
            with self._lock:
                self._window = []

    def _on_crash(self, exc: BaseException) -> None:
        """Supervisor hook for a crashed scheduler iteration: every slot of
        the in-flight window resolves with a 503-able error (no waiter may
        hang on a dead thread), and a leaked pool session's HBM is freed.
        Arrivals still queued are NOT failed — the restarted loop serves
        them; replaying the FAILED window is the client's call, not ours."""
        with self._lock:
            window, self._window = self._window, []
        self.state.flight.record(
            "scheduler_crash", error=repr(exc)[:200],
            requests=[s.trace.request_id for s in window
                      if s.trace is not None][:8])
        self.state.flight.dump("scheduler_crash")
        err = exc if isinstance(exc, LifecycleError) else SchedulerCrashed(exc)
        for s in window:
            if not s.done.is_set():
                self._resolve_err(s, err)
        with self._lock:
            sess, self._active_sess = self._active_sess, None
            if sess is None:
                sess = self._keep_sess
            self._keep_sess = None
        if sess is not None:
            try:
                sess.close()
            except Exception:  # noqa: BLE001 — cleanup must not re-crash
                pass

    def _enqueue(self, slot) -> None:
        with self._lock:
            if self._supervisor is None:
                self._supervisor = Supervisor(
                    self._scheduler_loop, self._on_crash,
                    name="dllama-batch-scheduler")
            self._supervisor.start()
        self._arrivals.put(slot)

    def _wait_resolution(self, slot, tick_s: float = 0.25) -> None:
        """Wait for the scheduler to resolve ``slot`` — BOUNDED: gives up
        with a typed error when the scheduler thread is dead (supervisor
        exhausted) or the slot's deadline passed long enough ago that the
        between-chunks enforcement clearly isn't coming (wedged device
        dispatch). submit() must never block forever."""
        while not slot.done.wait(tick_s):
            if not self.scheduler_alive:
                raise SchedulerCrashed(
                    RuntimeError("scheduler thread is not running"))
            dl = slot.deadline
            if dl is not None and dl.remaining() < -self.DEADLINE_GRACE_S:
                raise dl.error()

    def submit(self, prompt_tokens: list, max_tokens: int,
               sampler: SamplerConfig, deadline: Deadline = None,
               cancel: CancelToken = None, trace=None,
               slo_class: str = "interactive") -> list:
        """Blocks until this request's tokens are decoded (by the scheduler
        thread's pool). Thread-safe; raises the decode's failure as
        RuntimeError (typed LifecycleError for deadline/cancel/crash)."""
        slot = self._Slot(list(prompt_tokens), max_tokens, sampler,
                          streaming=False, deadline=deadline, cancel=cancel,
                          trace=trace, slo_class=slo_class)
        self._enqueue(slot)
        self._wait_resolution(slot)
        if slot.error is not None:
            raise slot.error
        return slot.tokens

    def submit_stream(self, prompt_tokens: list, max_tokens: int,
                      sampler: SamplerConfig, deadline: Deadline = None,
                      cancel: CancelToken = None, trace=None,
                      ckpt_every: int = 0, slo_class: str = "interactive"):
        """Yields bursts (lists) of token ids as the pool decodes — from
        admission, not from batch completion. Raises the decode failure as
        RuntimeError. A set ``cancel`` token ends the generator (the
        scheduler releases the row's slot at its next chunk boundary).
        ``ckpt_every`` > 0 interleaves ``("ckpt", export_snapshot)``
        markers into the yielded stream every that-many tokens — the SSE
        writer serializes them into checkpoint frames for the router."""
        slot = self._Slot(list(prompt_tokens), max_tokens, sampler,
                          streaming=True, deadline=deadline, cancel=cancel,
                          trace=trace, ckpt_every=ckpt_every,
                          slo_class=slo_class)
        self._enqueue(slot)
        return self._drain_stream(slot, cancel)

    def _drain_stream(self, slot, cancel):
        """Consume a streaming slot's queue: yield bursts — token-id lists
        interleaved with ``("ckpt", snapshot)`` markers when the slot
        checkpoints — until the terminal item (None = clean end,
        Exception = raised)."""
        while True:
            try:
                item = slot.queue.get(timeout=0.25)
            except queue_mod.Empty:
                if cancel is not None and cancel.cancelled:
                    return  # the writer stopped consuming; don't spin
                if not self.scheduler_alive:
                    raise SchedulerCrashed(
                        RuntimeError("scheduler thread is not running"))
                dl = slot.deadline
                if dl is not None and dl.remaining() < -self.DEADLINE_GRACE_S:
                    raise dl.error()
                continue
            if item is None:
                break
            if isinstance(item, Exception):
                raise item
            yield item

    # -- disaggregation jobs (role-aware serving) -------------------------
    def submit_prefill(self, prompt_tokens: list, max_tokens: int,
                       sampler: SamplerConfig, deadline: Deadline = None,
                       trace=None) -> tuple:
        """Prefill ``prompt_tokens`` in the paged pool, decode ONE chunk,
        and migrate: returns ``(export_snapshot, emitted_tokens)``. The
        snapshot is None when the row finished inside its first chunk (a
        stop token or a one-chunk budget) — then ``emitted_tokens`` is the
        complete row and nothing migrates. Raises like :meth:`submit`."""
        slot = self._Slot(list(prompt_tokens), max_tokens, sampler,
                          streaming=False, deadline=deadline,
                          trace=trace, kind="prefill")
        self._enqueue(slot)
        self._wait_resolution(slot)
        if slot.error is not None:
            raise slot.error
        return slot.export, slot.tokens

    def submit_import(self, snap: dict, deadline: Deadline = None,
                      trace=None) -> list:
        """Admit a migrated row from a decoded kv_transfer snapshot and
        block until its remaining tokens are decoded. Raises like
        :meth:`submit` (a pool that can't fit the row raises RuntimeError
        — the caller's cue to fall back to re-prefilling)."""
        slot = self._import_slot(snap, deadline=deadline, trace=trace,
                                 streaming=False)
        self._enqueue(slot)
        self._wait_resolution(slot)
        if slot.error is not None:
            raise slot.error
        return slot.tokens

    def submit_import_stream(self, snap: dict, deadline: Deadline = None,
                             cancel: CancelToken = None, trace=None,
                             ckpt_every: int = 0):
        """Streaming variant of :meth:`submit_import`: yields bursts of
        freshly decoded token ids (the carried already-emitted tokens are
        the CALLER's to prepend — they were streamed by the exporter's
        chunk, not decoded here). ``ckpt_every`` keeps the resumed row
        checkpointing, so a SECOND death during resume is itself
        resumable."""
        slot = self._import_slot(snap, deadline=deadline, cancel=cancel,
                                 trace=trace, streaming=True,
                                 ckpt_every=ckpt_every)
        self._enqueue(slot)
        return self._drain_stream(slot, cancel)

    def _import_slot(self, snap: dict, deadline=None, cancel=None,
                     trace=None, streaming: bool = False,
                     ckpt_every: int = 0):
        sampler = SamplerConfig(temperature=float(snap["temp"]),
                                topp=float(snap["topp"]), seed=0)
        steps = max(1, int(snap["budget"]) - int(snap["emitted"]))
        return self._Slot(list(snap["prompt"]), steps, sampler,
                          streaming=streaming, deadline=deadline,
                          cancel=cancel, trace=trace, kind="import",
                          snap=snap, ckpt_every=ckpt_every)


class ServerState:
    """Everything the handler needs; one instance per server."""

    def __init__(self, engine, tokenizer, cfg, model_name: str, template: str = "llama3",
                 default_sampler: SamplerConfig = SamplerConfig(),
                 default_seed: int = None, spec_draft: int = 0,
                 session_cache: int = 2, batch_window_ms: float = 0.0,
                 batch_max: int = 8, batch_chunk: int = 8,
                 prefill_chunk: int = -1, kv_buckets: int = 1,
                 kv_bucket_min: int = 0, kv_pages: int = 0,
                 request_timeout: float = 0.0, queue_depth: int = 64,
                 metrics=None, log_json: bool = False,
                 log_prompts: bool = False, log_stream=None, flight=None,
                 role: str = "both", ckpt_interval: int = 32,
                 slo_classes=None, ts_interval: float = 1.0,
                 burn_short: float = 60.0, burn_long: float = 300.0):
        """``default_seed``: seed for requests that send none — None means a
        fresh time-based seed per request (the launch-flag --seed plumbs in
        here so an operator can make the whole server reproducible).
        ``spec_draft`` > 0 serves requests with prompt-lookup speculative
        decoding (Engine.generate_spec — multiple tokens per device step on
        repetitive text). Responses are byte-identical to the plain path at
        any temperature: greedy verifies against argmax, sampled against the
        same per-request key chain. ``session_cache``: how many conversation
        KV states to keep resident (each holds a full KV cache in HBM —
        size this against seq_len x n_layers x kv_dim x cache dtype).
        ``request_timeout``: per-request wall-clock budget in seconds
        (--request-timeout; 0 = unlimited) — an expired request 504s and
        its decode row is released at the next chunk boundary.
        ``queue_depth``: max concurrent requests admitted (--queue-depth);
        overflow is rejected 429 + Retry-After instead of queuing
        unboundedly.
        ``prefill_chunk``: prompt tokens per incremental prefill piece in
        the pooled path (--prefill-chunk; <0 = auto, 0 = monolithic).
        ``kv_buckets``/``kv_bucket_min``: length-bucketed KV slot pools
        (--kv-buckets/--kv-bucket-min) — more resident rows at the same
        modeled HBM budget when traffic skews short.
        ``kv_pages``: tokens per KV page (--kv-pages; 0 = slab modes) —
        paged KV pool with a copy-on-write radix prefix cache: shared
        prompt prefixes are aliased instead of re-prefilled, and growing
        rows append pages instead of migrating slabs.
        ``metrics``: observability.MetricsRegistry to register server-layer
        series on (None = the process-wide default registry, which the
        engine/lifecycle/weights layers already share — one /metrics scrape
        covers all four layers). ``log_json``: emit one structured JSON
        line per finished request to ``log_stream`` (default stderr).
        ``log_prompts``: include raw prompt text in those logs — OFF by
        default; logs carry only token counts and a sha256 prompt digest.
        ``role``: this replica's disaggregation role (--role): "prefill"
        (the fleet router sends it new prompts and migrates their KV to a
        decode replica at first token), "decode" (receives migrated rows)
        or "both" (the default — a colocated replica). The role only
        steers the ROUTER's placement; every replica answers every
        endpoint, so a lone "both" fleet behaves exactly as before.
        ``ckpt_interval``: default mid-stream checkpoint cadence in
        emitted tokens (--ckpt-interval) for streams that opt in via the
        ``X-Dllama-Ckpt`` header without naming their own K; 0 disables
        even opted-in checkpointing. A stream never checkpoints unless
        the request asks — direct (router-less) clients never see
        checkpoint control frames.
        ``slo_classes``: per-class admission policy (--slo-classes) — a
        {name: lifecycle.SLOClass} dict or the raw spec string (see
        lifecycle.parse_slo_classes). Defaults leave every lane bounded
        only by ``queue_depth``, i.e. exactly the single-class behavior.
        ``ts_interval``: time-series sampler cadence in seconds
        (--ts-interval; 0 disables history + burn-rate alerts).
        ``burn_short``/``burn_long``: the burn-rate engine's evaluation
        windows (--burn-short/--burn-long) against the class ``ttft=``/
        ``tpot=``/``err=`` targets."""
        self.engine = engine
        self.tokenizer = tokenizer
        self.cfg = cfg
        self.model_name = model_name
        self.template = template
        self.default_sampler = default_sampler
        self.default_seed = default_seed
        self.spec_draft = spec_draft
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be prefill/decode/both, got {role!r}")
        self.role = role
        self.ckpt_interval = max(0, int(ckpt_interval))
        self.session_cache = max(1, session_cache)
        #: HBM bound shared by the batcher AND the `n` parameter: a batch's
        #: KV cache holds this many full-context caches
        self.batch_max = max(1, batch_max)
        self.request_timeout = max(0.0, request_timeout or 0.0)
        #: per-class admission policy: parsed --slo-classes (dict form
        #: accepted so in-process tests can hand SLOClass objects straight
        #: in; every class in lifecycle.SLO_CLASSES has an entry)
        self.slo_classes = (parse_slo_classes(slo_classes)
                            if isinstance(slo_classes, str) or slo_classes
                            is None else dict(slo_classes))
        #: bounded admission: EVERY completion request (solo or batched)
        #: acquires before doing work, so backpressure is a fast 429 at the
        #: door rather than an unbounded pile of blocked HTTP threads —
        #: lane-scoped (429s carry class-aware Retry-After) under classes
        self.gate = AdmissionGate(queue_depth, classes=self.slo_classes)
        self.lock = threading.Lock()  # engine serves one request at a time
        # -- observability: server-layer series (HTTP + per-request latency).
        # Registered BEFORE the batcher so its scheduler-layer handles share
        # the same registry instance.
        self.metrics = (metrics if metrics is not None
                        else observability.default_registry())
        #: the process's flight-recorder ring (GET /debug/flight; dumped on
        #: crash/504/SIGTERM). The process-global instance by default so the
        #: lifecycle layer's module-level hooks land in the same ring;
        #: in-process multi-replica tests pass their own for isolation.
        self.flight = (flight if flight is not None
                       else observability.flight_recorder())
        self.log_json = bool(log_json)
        self.log_prompts = bool(log_prompts)
        self.log_stream = log_stream
        self.started_at = time.time()
        #: replica identity: start nonce + (once bound) the listen port.
        #: Survives nothing — that is the point: a crash-restart mints a NEW
        #: generation, so federated series and router logs can tell "the
        #: same replica came back" from "a stale snapshot of the old one".
        self.start_nonce = uuid.uuid4().hex[:8]
        self.replica_id = f"0-{self.start_nonce}"  # port set by create_server
        reg = self.metrics
        self._m_http = reg.counter(
            "dllama_http_requests_total",
            "HTTP responses written, by route and status code",
            ("route", "code"))
        self._m_ttft = reg.histogram(
            "dllama_ttft_ms",
            "Time to first token (from request arrival), by decode path",
            ("path",))
        self._m_tpot = reg.histogram(
            "dllama_tpot_ms",
            "Mean time per output token after the first, by decode path",
            ("path",))
        self._m_queue_wait = reg.histogram(
            "dllama_queue_wait_ms",
            "Arrival-to-scheduling wait (admission + batching window)")
        # per-SLO-class latency series: the workload harness's per-class
        # SLO gates (and `cli top`'s lane view) read these off the
        # federated /metrics/fleet
        self._m_class_ttft = reg.histogram(
            "dllama_class_ttft_ms",
            "Time to first token (from request arrival), by SLO class",
            ("slo_class",))
        self._m_class_tpot = reg.histogram(
            "dllama_class_tpot_ms",
            "Mean time per output token after the first, by SLO class",
            ("slo_class",))
        self._m_tokens_in = reg.counter(
            "dllama_prompt_tokens_total", "Prompt tokens accepted")
        self._m_tokens_out = reg.counter(
            "dllama_completion_tokens_total", "Completion tokens generated")
        # token-COUNT distributions: power-of-two buckets (TOKEN_BUCKETS),
        # NOT the latency boundaries — each bucket reads directly as "which
        # KV bucket would this request land in"
        self._m_prompt_hist = reg.histogram(
            "dllama_prompt_tokens",
            "Prompt length per request, in power-of-two token buckets",
            buckets=observability.TOKEN_BUCKETS)
        self._m_completion_hist = reg.histogram(
            "dllama_completion_tokens",
            "Completion length per request, in power-of-two token buckets",
            buckets=observability.TOKEN_BUCKETS)
        self._m_sse_disconnect = reg.counter(
            "dllama_sse_disconnects_total",
            "Streaming responses whose client vanished mid-stream (the "
            "decode row is cancelled at its next chunk boundary)")
        # disaggregated serving: KV page-stream handoff between replicas.
        # outcome="error" moves when the kv_export/kv_import fault sites
        # fire — a failed transfer is machine-visible fleet-wide via the
        # router's federated /metrics/fleet, same as every dllama_* series
        self._m_kv_exports = reg.counter(
            "dllama_kv_transfer_exports_total",
            "KV page-stream export attempts (a migrating row leaving "
            "this replica), by outcome", ("outcome",))
        self._m_kv_imports = reg.counter(
            "dllama_kv_transfer_imports_total",
            "KV page-stream import attempts (a migrating row arriving at "
            "this replica), by outcome", ("outcome",))
        self._m_kv_bytes = reg.counter(
            "dllama_kv_transfer_bytes_total",
            "Framed KV page-stream wire bytes, by direction (in/out)",
            ("direction",))
        self._m_kv_pages = reg.counter(
            "dllama_kv_transfer_pages_total",
            "KV pages shipped on the transfer wire, by direction (in/out)",
            ("direction",))
        # mid-stream failover: periodic session checkpoints shipped in-band
        # to the router. outcome="error" moves when the ckpt_write fault
        # site fires (or a live export fails) — a failed checkpoint only
        # shrinks resume coverage, never the stream
        self._m_ckpt_writes = reg.counter(
            "dllama_ckpt_writes_total",
            "Mid-stream session checkpoint attempts (every --ckpt-interval "
            "emitted tokens on an opted-in stream), by outcome",
            ("outcome",))
        # info-style gauge (value 1, identity in the labels): the resolved
        # TP wire format, overlap mode and reduce direction ride /metrics —
        # and therefore the router's federated /metrics/fleet — so a q80
        # request that was warned-and-dropped to plain gathers (or a
        # tp_reduce that declined) is machine-visible fleet-wide
        from dllama_tpu.serving.protocol import TP_WIRE_INFO_LABELS

        reg.gauge("dllama_tp_wire_info",
                  "Resolved TP wire/overlap/reduce configuration (labels "
                  "carry the values; constant 1)",
                  labelnames=TP_WIRE_INFO_LABELS).set(
            1.0,
            tp_wire=getattr(engine, "tp_wire", "plain"),
            tp_overlap=("on" if getattr(engine, "tp_overlap_active", False)
                        else "off"),
            tp_reduce=getattr(engine, "tp_reduce", "off"))
        reg.gauge("dllama_batch_queue_depth",
                  "Arrivals waiting for the batch scheduler").set_function(
            lambda: float(self.batcher.queue_depth())
            if self.batcher is not None else 0.0)
        reg.gauge("dllama_slots_occupied",
                  "Occupied slots of the live pooled decode session"
                  ).set_function(
            lambda: float(self.batcher.occupancy()[0])
            if self.batcher is not None else 0.0)
        # --batch-window > 0: requests (greedy or sampled, streaming or
        # not) that arrive within the window share a continuously batched
        # slot-pool decode (Batcher) — single-device or tensor-parallel
        # alike; later arrivals are admitted into freed slots between
        # fused chunks of --batch-chunk steps. Off by default: batching
        # adds up to window_ms latency per request and only pays off under
        # concurrency.
        self.batcher = (
            Batcher(self, batch_window_ms, max_batch=batch_max,
                    chunk=batch_chunk, prefill_chunk=prefill_chunk,
                    kv_buckets=bool(kv_buckets),
                    kv_bucket_min=kv_bucket_min,
                    kv_pages=kv_pages, slo_classes=self.slo_classes)
            if batch_window_ms > 0 else None
        )
        # prefix cache: KV state + token history of recent completions, LRU.
        # Multi-turn chats resend the whole conversation; when a new prompt
        # extends a cached history, only the suffix is prefilled — and with
        # N slots, INTERLEAVED conversations each keep their own hot state.
        # The reference restarts pos=0 with no reuse every request
        # (`/root/reference/src/apps/dllama-api/dllama-api.cpp:257`).
        self._sessions: list = []  # [(tokens, session)], oldest first
        # -- continuous observability (obsv/): bounded metric history
        # (GET /metrics/history) + SLO burn-rate alerts (GET /alerts),
        # sampled off this state's registry. The sampler THREAD starts
        # with the HTTP listener (create_server), so bare in-process
        # states stay thread-free; --ts-interval 0 disables the whole
        # subsystem (the BENCH_OBS off-leg).
        self.ts_store = TimeSeriesStore()
        self.burn_engine = BurnRateEngine(
            self.ts_store, self.slo_classes, reg, flight=self.flight,
            short_s=burn_short, long_s=burn_long)
        self.sampler = Sampler(reg, self.ts_store, interval_s=ts_interval,
                               hooks=(self.burn_engine.evaluate,))

    @staticmethod
    def _session_matches(cached: list, session, prompt_tokens: list) -> bool:
        """THE prefix-match predicate, shared by the claim
        (take_prefix_session) and the lock-free peek (has_prefix_session) so
        the batcher gate can never drift from what the solo path would
        actually claim: cached history must be a non-empty prefix of the
        prompt, and an exact-length match needs a pending token (an empty
        suffix with nothing pending would leave generate() with no input)."""
        if not (0 < len(cached) <= len(prompt_tokens)):
            return False
        if prompt_tokens[: len(cached)] != cached:
            return False
        return not (len(cached) == len(prompt_tokens)
                    and session.pending_token is None)

    def has_prefix_session(self, prompt_tokens: list) -> bool:
        """Read-only peek: does any cached session's history prefix
        ``prompt_tokens``? Used WITHOUT the engine lock by the batcher gate
        (a lock-free snapshot is safe under the GIL; a racy miss just costs
        one re-prefill, a racy hit routes one request solo) — a multi-turn
        conversation must keep its KV reuse instead of re-prefilling its
        whole history through the batch path every turn."""
        return any(self._session_matches(cached, session, prompt_tokens)
                   for cached, session in list(self._sessions))

    def take_prefix_session(self, prompt_tokens: list) -> tuple:
        """Returns (session, tokens_to_feed). Claims (removes) the cached
        session with the LONGEST history that ``prompt_tokens`` extends;
        (None, prompt_tokens) when no entry matches (from-scratch prefill —
        unmatched entries stay cached for their own conversations). Call
        under lock."""
        best, best_len = -1, 0
        for i, (cached, session) in enumerate(self._sessions):
            if not self._session_matches(cached, session, prompt_tokens):
                continue
            if len(cached) > best_len:
                best, best_len = i, len(cached)
        if best < 0:
            # miss at capacity: evict the oldest entry BEFORE the caller
            # allocates a fresh cache, or peak HBM would transiently hold
            # session_cache + 1 full KV caches during the prefill
            if len(self._sessions) >= self.session_cache:
                self._evict_oldest()
            return None, prompt_tokens
        cached, session = self._sessions.pop(best)
        return session, prompt_tokens[len(cached):]

    def _evict_oldest(self) -> None:
        """Drop the LRU session and free its KV cache's device buffers NOW —
        waiting for GC would transiently hold an extra cache in HBM."""
        import jax

        _, old = self._sessions.pop(0)
        for leaf in jax.tree.leaves(old.cache):
            leaf.delete()

    def store_prefix_session(self, tokens: list, session) -> None:
        """Cache the post-request state: ``tokens`` = every token fed or
        sampled this request (the session's pending token last); evicts
        beyond capacity."""
        self._sessions.append((list(tokens), session))
        while len(self._sessions) > self.session_cache:
            self._evict_oldest()

    def open_stream(self, prompt_tokens: list, feed_tokens: list, session,
                    max_tokens: int, sampler: SamplerConfig):
        """THE solo token-stream dispatch, shared by the HTTP solo path and
        the batcher's singleton delegation so the spec-vs-plain branch and
        the n-gram history arithmetic can never drift. A --spec-draft
        server speculates (generate_spec is exact at any temperature);
        ``history`` tells its n-gram index about tokens already consumed
        into the claimed session's cache (the cached prefix minus its
        pending token, when it has one) so drafts match across earlier
        turns of the chat."""
        stop_ids = self.stop_token_ids()
        if self.spec_draft > 0:
            pending = 1 if (session is not None
                            and session.pending_token is not None) else 0
            n_consumed = len(prompt_tokens) - len(feed_tokens) - pending
            return self.engine.generate_spec(
                feed_tokens, max_tokens, session=session,
                stop_tokens=stop_ids, draft_len=self.spec_draft,
                history=prompt_tokens[:n_consumed] if session else None,
                sampler=sampler,
            )
        return self.engine.generate(
            feed_tokens, max_tokens, session=session,
            stop_tokens=stop_ids, sampler=sampler,
        )

    def stop_token_ids(self) -> tuple:
        """Hard stop ids: EOS plus the Llama-3 end-of-turn token when the
        vocab carries one. Single source for the solo and batched paths."""
        ids = tuple(i for i in (self.tokenizer.eos_id,) if i >= 0)
        eot = self.tokenizer.piece_id(b"<|eot_id|>")
        return ids + ((eot,) if eot >= 0 else ())

    def begin_drain(self) -> None:
        """SIGTERM path: stop admitting (new requests 503), let in-flight
        requests finish. The scheduler loop exits cleanly once its queue is
        empty and the gate reports draining."""
        self.gate.begin_drain()

    def readiness(self) -> tuple:
        """(ready, info) for the /ready probe. NOT ready while draining or
        while the scheduler thread is dead (supervisor mid-restart); the
        info dict reports the load picture either way so operators see WHY."""
        batcher = self.batcher
        occupied, total = (batcher.occupancy() if batcher is not None
                           else (0, self.batch_max))
        scheduler_alive = (batcher.scheduler_alive
                          if batcher is not None else True)
        ready = not self.gate.draining and scheduler_alive
        kv = (batcher.kv_info() if batcher is not None
              else {"kv_tokens_reserved": 0, "kv_tokens_budget": 0,
                    "kv_rows": {}, "kv_pages_free": 0, "kv_pages_total": 0,
                    "prefix_hit_rate": 0.0})
        return ready, {
            "status": "ready" if ready else "not_ready",
            # identity + clock: the router keys federated series and its
            # generation-change log on replica_id, and estimates this
            # replica's trace-clock offset (skew + RTT/2) from time_us
            # against its own probe send/recv timestamps
            "replica_id": self.replica_id,
            # disaggregation role: the router routes new prompts to
            # prefill-capable replicas and migrated rows to decode-capable
            # ones off this single field
            "role": self.role,
            "started_at": round(self.started_at, 3),
            "time_us": observability.mono_to_us(),
            "draining": self.gate.draining,
            "scheduler_alive": scheduler_alive,
            "scheduler_crashes": (batcher.crash_count
                                  if batcher is not None else 0),
            "inflight": self.gate.depth,
            "queue_capacity": self.gate.capacity,
            "queue_depth": (batcher.queue_depth()
                            if batcher is not None else 0),
            "slots_occupied": occupied,
            "slots_total": total,
            # TP wire resolution, machine-visible: a q80 request the CLI
            # warned-and-dropped reads back "plain" here, and tp_overlap
            # says whether the microbatch-overlap programs were actually
            # built (with the drop reason when not)
            "tp_wire": getattr(self.engine, "tp_wire", "plain"),
            "tp_overlap": ("on" if getattr(self.engine, "tp_overlap_active",
                                           False) else "off"),
            "tp_overlap_reason": getattr(self.engine, "tp_overlap_reason",
                                         "not requested"),
            # row-parallel reduce direction, same contract: the resolved
            # mode ("off" when declined) plus the machine-visible reason
            "tp_reduce": getattr(self.engine, "tp_reduce", "off"),
            "tp_reduce_reason": getattr(self.engine, "tp_reduce_reason",
                                        "not requested"),
            # decode kernel-fusion resolution (flash / fused norm / fused
            # rope+cache): the env flags resolved against what this
            # engine's weights and TP path can actually engage
            "kernel_fusions": getattr(self.engine, "kernel_fusions", {}),
            # per-SLO-class lane picture: gate in-flight depth + the
            # scheduler's waiting/resident/preempted counts. The router's
            # class-aware scoring penalizes a replica by ITS lane's
            # pressure, not the aggregate
            "classes": self._class_readiness(),
            **kv,
        }

    def _class_readiness(self) -> dict:
        """{class: {inflight, capacity, waiting, resident, preempted}} —
        the per-lane slice of the readiness payload."""
        depths = self.gate.class_depths()
        stats = (self.batcher.class_stats() if self.batcher is not None
                 else {})
        out = {}
        for name in self.slo_classes:
            lane = stats.get(name, {})
            out[name] = {
                "inflight": depths.get(name, 0),
                "capacity": self.gate.class_capacity(name),
                "waiting": lane.get("waiting", 0),
                "resident": lane.get("resident", 0),
                "preempted": lane.get("preempted", 0),
            }
        return out

    def finish_request(self, trace: RequestTrace) -> None:
        """Per-request telemetry sink, called once per completion request
        (success, typed rejection, or failure alike): observe the latency
        histograms, append the request's spans to the DLLAMA_TRACE file,
        and emit the structured JSON log line (--log-json). Prompt text
        never reaches the log unless --log-prompts: the record carries
        token counts and a sha256 digest instead."""
        path = trace.path or "none"
        slo_class = trace.slo_class or "interactive"
        if trace.ttft_ms is not None:
            self._m_ttft.observe(trace.ttft_ms, path=path)
            self._m_class_ttft.observe(trace.ttft_ms, slo_class=slo_class)
        if trace.tpot_ms is not None:
            self._m_tpot.observe(trace.tpot_ms, path=path)
            self._m_class_tpot.observe(trace.tpot_ms, slo_class=slo_class)
        if trace.queue_wait_ms is not None:
            self._m_queue_wait.observe(trace.queue_wait_ms)
        if trace.tokens_in:
            self._m_tokens_in.inc(trace.tokens_in)
            self._m_prompt_hist.observe(float(trace.tokens_in))
        if trace.tokens_out:
            self._m_tokens_out.inc(trace.tokens_out)
            self._m_completion_hist.observe(float(trace.tokens_out))
        observability.emit_trace_events(trace.trace_events())
        self.flight.record(
            "request_end", request_id=trace.request_id, status=trace.status,
            finish_reason=trace.finish_reason, tokens_out=trace.tokens_out)
        if trace.status == 504 or trace.finish_reason == "timeout":
            # a blown deadline is an incident worth its black box: the dump
            # shows what the gate/scheduler were doing while budget burned
            self.flight.dump("deadline")
        if self.log_json:
            rec = trace.record()
            if self.log_prompts and trace.prompt_text is not None:
                rec["prompt"] = trace.prompt_text
            observability.log_json_line(rec, stream=self.log_stream)

    def stats(self) -> dict:
        """JSON stats for GET /stats: the readiness picture plus latency
        percentiles (served from each histogram's raw-sample reservoir) —
        the human-curl view of what /metrics exposes for scrapers."""
        _, info = self.readiness()
        snap = self.metrics.snapshot()
        return {
            "model": self.model_name,
            "replica_id": self.replica_id,
            "started_at": round(self.started_at, 3),
            "uptime_s": round(time.time() - self.started_at, 1),
            "load": info,
            "metrics": snap,
        }

    def build_prompt(self, messages: list) -> str:
        """Render a full conversation (the API is stateless: each request
        carries all messages, same as the reference, `dllama-api.cpp:173-181`)."""
        if self.template == "llama3":
            return render_llama3_chat(messages)
        system = ""
        parts = []
        first = True
        for m in messages:
            if m["role"] == "system":
                system = m["content"]
            elif m["role"] == "user":
                parts.append(render_llama2_turn(m["content"], system, first))
                first = False
            elif m["role"] == "assistant":
                parts.append(f" {m['content']} ")
        return "".join(parts)


def _completion_id() -> str:
    return "chatcmpl-" + uuid.uuid4().hex[:16]


class OpenAIHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    state: ServerState = None  # set by create_server

    def log_message(self, fmt, *args):  # quiet; the CLI prints its own lines
        pass

    # -- helpers ----------------------------------------------------------
    #: every HTTP response path funnels through here or _send_sse_headers,
    #: so the request-id echo and the http-requests counter cover 200s,
    #: SSE streams, and every 4xx/5xx alike
    _KNOWN_ROUTES = ("/v1/chat/completions", "/chat/completions",
                     "/v1/models", "/health", "/healthz", "/ready",
                     "/metrics", "/metrics/history", "/alerts",
                     "/stats", "/debug/flight",
                     "/v1/prefill", "/v1/kv/import", "/v1/kv/resume")

    def _route(self) -> str:
        """Route label for the HTTP counter: known paths verbatim, anything
        else bucketed as "other" so probe scans can't explode cardinality."""
        p = self.path.split("?", 1)[0]
        return p if p in self._KNOWN_ROUTES else "other"

    def _begin_request(self) -> None:
        """Per-request handler state: the request id (client-supplied
        X-Request-Id when sane, freshly minted otherwise) echoed on EVERY
        response, the router's hop span (X-Dllama-Parent-Span) for trace
        stitching, and the not-yet-emitted trace for POSTs."""
        self._rid = observability.sanitize_request_id(
            self.headers.get(HDR_REQUEST_ID))
        self._parent_span = observability.sanitize_parent_span(
            self.headers.get(HDR_PARENT_SPAN))
        self._trace = None
        self._t_begin = time.monotonic()

    def _ckpt_request(self) -> tuple:
        """Parse the router's ``X-Dllama-Ckpt`` / ``X-Dllama-Ckpt-Wire``
        headers into ``(ckpt_every, wire)``. 0 = checkpointing not
        requested — or disabled on this replica (--ckpt-interval 0
        outranks any header); a bare/"auto" value takes the replica's
        --ckpt-interval default. An unknown wire falls back to f32, the
        bit-exact mode a resume can always trust."""
        st = self.state
        raw = (self.headers.get(HDR_CKPT) or "").strip().lower()
        if not raw or st.ckpt_interval <= 0:
            return 0, "f32"
        k = (st.ckpt_interval if not raw.isdigit() else int(raw))
        wire = (self.headers.get(HDR_CKPT_WIRE) or "f32").strip()
        if wire not in kv_transfer.WIRE_MODES:
            wire = "f32"
        return max(0, k), wire

    def _start_deadline(self) -> "Deadline":
        """Effective wall-clock budget for this request: the class lane's
        configured deadline when one is set (the SLO the lane promised its
        clients), else the server-wide --request-timeout."""
        st = self.state
        lane = st.gate.deadline_for(getattr(self, "_slo_class",
                                            "interactive"))
        return Deadline.start(lane or st.request_timeout)

    def _count(self, code: int) -> None:
        self.state._m_http.inc(route=self._route(), code=str(code))
        if self._trace is not None and self._trace.status == 0:
            self._trace.status = code
        if code >= 500:
            self.state.flight.record("http_5xx", code=code,
                                     route=self._route(),
                                     request_id=self._rid)

    def _server_timing(self) -> str:
        """Server-Timing value for THIS response: the request trace's phase
        durations when one exists (the router's hop attribution reads
        queue/prefill/decode), handler wall time otherwise — every endpoint
        emits the header (CONTRIBUTING rule), even plain GETs."""
        st = (observability.server_timing_header(self._trace)
              if self._trace is not None else "")
        total = f"total;dur={(time.monotonic() - self._t_begin) * 1e3:.3f}"
        return f"{st}, {total}" if st else total

    def _json(self, code: int, obj: dict, headers: dict = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.send_header(HDR_REQUEST_ID, self._rid)
        self.send_header(HDR_SERVER_TIMING, self._server_timing())
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self._count(code)
        self.wfile.write(body)

    def _send_sse_headers(self, extra: dict = None) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.send_header(HDR_REQUEST_ID, self._rid)
        # headers leave before decode runs: only the phases known NOW (queue
        # wait at best) appear; the router attributes the rest to stream time
        self.send_header(HDR_SERVER_TIMING, self._server_timing())
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self._count(200)

    def _error(self, code: int, message: str) -> None:
        self._json(code, {"error": {"message": message,
                                    "type": "invalid_request_error",
                                    "request_id": self._rid}})

    def _lifecycle_error(self, e: LifecycleError) -> None:
        """Speak a typed lifecycle rejection: its own HTTP status (429
        queue-full, 503 draining/crash, 504 deadline) and a Retry-After
        header when the error carries one."""
        headers = {}
        if e.retry_after_s is not None:
            headers["Retry-After"] = str(max(1, int(round(e.retry_after_s))))
        self._json(e.http_status,
                   {"error": {"message": str(e), "type": "server_error",
                              "request_id": self._rid}},
                   headers=headers)

    # -- routes -----------------------------------------------------------
    def do_GET(self):
        self._begin_request()
        st = self.state
        if self.path == "/v1/models":
            self._json(200, {
                "object": "list",
                "data": [{
                    "id": st.model_name,
                    "object": "model",
                    "created": int(time.time()),
                    "owned_by": "dllama_tpu",
                }],
            })
        elif self.path in ("/health", "/healthz"):
            # LIVENESS: 200 whenever the process can answer — a draining or
            # scheduler-crashed server is still alive (don't restart it);
            # readiness is /ready's job. The body carries the same load
            # picture as /ready so one curl answers "alive AND why".
            _, info = st.readiness()
            self._json(200, {
                "status": "ok",
                "scheduler_alive": info["scheduler_alive"],
                "crash_count": info["scheduler_crashes"],
                "queue_depth": info["queue_depth"],
            })
        elif self.path == "/ready":
            # READINESS: should a load balancer send traffic here?
            ready, info = st.readiness()
            info["crash_count"] = info["scheduler_crashes"]
            self._json(200 if ready else 503, info)
        elif self.path == "/metrics":
            # Prometheus text exposition (hand-rolled, stdlib only): every
            # layer's series — server/scheduler (this file), lifecycle gate,
            # engine decode, weight integrity — off one registry
            body = st.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.send_header(HDR_REQUEST_ID, self._rid)
            self.end_headers()
            self._count(200)
            self.wfile.write(body)
        elif self.path.split("?", 1)[0] == "/metrics/history":
            # the time-series ring as windowed JSON: what every sampled
            # series did over the last ?window= seconds (default 300)
            self._json(200, dict(
                st.ts_store.window(parse_window(self.path)),
                replica_id=st.replica_id))
        elif self.path == "/alerts":
            # live SLO burn-rate picture: one entry per configured
            # (class, signal) target, firing or resolved
            self._json(200, dict(st.burn_engine.alerts_payload(),
                                 replica_id=st.replica_id))
        elif self.path == "/stats":
            self._json(200, st.stats())
        elif self.path == "/debug/flight":
            # the live flight-recorder ring, no dump required: what this
            # process saw happen recently, for incident triage and for the
            # router's aggregated fleet view
            self._json(200, dict(st.flight.snapshot(),
                                 replica_id=st.replica_id))
        else:
            self._error(404, f"unknown path {self.path}")

    def do_POST(self):
        self._begin_request()
        if self.path in ("/v1/chat/completions", "/chat/completions"):
            handle, binary = self._handle_completions, False
        elif self.path == "/v1/prefill":
            # disaggregated serving, hop 1: prefill + first chunk here,
            # then answer either the finished completion or a framed KV
            # page stream for the router to hand a decode replica
            handle, binary = self._handle_prefill, False
        elif self.path == "/v1/kv/import":
            # hop 2: admit a migrated row warm from its page stream and
            # decode the rest (body is kv_transfer-framed bytes, not JSON)
            handle, binary = self._handle_kv_import, True
        elif self.path == "/v1/kv/resume":
            # mid-stream failover: admit a dead sibling's checkpointed
            # session and continue its SSE stream bit-identically (body
            # is the checkpoint's kv_transfer-framed bytes)
            handle, binary = self._handle_kv_resume, True
        else:
            self._error(404, f"unknown path {self.path}")
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            req = body if binary else json.loads(body or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._error(400, f"bad JSON body: {e}")
            return
        # one trace per completion attempt — ALSO for typed rejections
        # (429/503/504), so rejected request ids still appear in the
        # structured log and the latency histograms stay success-only.
        # A router-minted parent span stitches this trace under the
        # router's proxy span in the merged fleet timeline.
        trace = self._trace = RequestTrace(self._rid,
                                           parent_span=self._parent_span)
        trace.model = self.state.model_name
        # SLO lane: X-Dllama-Class names the request's class. An UNKNOWN
        # class is a 400, never a silent default — a typo'd "bulk" job
        # must not land in (and blow) the interactive lane
        slo_class = (self.headers.get(HDR_CLASS)
                     or "interactive").strip().lower()
        if slo_class not in SLO_CLASSES:
            self._error(400, f"unknown SLO class {slo_class!r} "
                             f"(known: {', '.join(SLO_CLASSES)})")
            return
        trace.slo_class = self._slo_class = slo_class
        # bounded admission at the door: gate capacity covers EVERY in-
        # flight completion (solo and batched alike), so overflow is an
        # immediate 429 + Retry-After and a draining server answers 503
        # instead of stranding requests behind a closing engine. Lane-
        # scoped: a saturated batch lane 429s its own clients (with ITS
        # Retry-After) while interactive admission continues
        try:
            admitted_at = self.state.gate.acquire(slo_class)
        except LifecycleError as e:
            self._lifecycle_error(e)
            trace.finish_reason = "rejected"
            self.state.finish_request(trace)
            return
        trace.admission_depth = self.state.gate.depth
        self.state.flight.record("request_start", request_id=self._rid,
                                 depth=trace.admission_depth,
                                 slo_class=slo_class)
        try:
            handle(req, trace)
        except LifecycleError as e:
            # typed lifecycle end that escaped before any bytes were
            # written (non-streaming deadline/crash): speak its status
            try:
                self._lifecycle_error(e)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client vanished while we wrote the error body
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream (FIN -> BrokenPipe, RST ->
            # ConnectionReset); per-request isolation like the reference's
            # per-request catch (`dllama-api.cpp:347-351`)
        finally:
            self.state.gate.release(admitted_at, slo_class)
            self.state.finish_request(trace)

    def _stream_batched(self, base: dict, sampler: SamplerConfig,
                        prompt_tokens: list, max_tokens: int,
                        deadline: Deadline = None, trace=None,
                        carried: list = None, source=None,
                        cancel: CancelToken = None,
                        detector: StopDetector = None,
                        ckpt_every: int = 0, ckpt_wire: str = "f32",
                        resume_state: dict = None,
                        extra_headers: dict = None) -> None:
        """SSE streaming from the shared pool decode: bursts of up to
        batch-chunk tokens per event instead of one event per token (the
        granularity trade for sharing one device program across concurrent
        requests). ``detector`` enables stop-string truncation here (a
        tripped detector cancels the row at its next chunk boundary);
        without one, only stop TOKENS and budgets truncate — the batch
        gate still routes plain stop-string requests solo.

        Lifecycle: a write failure (client FIN/RST — or an injected
        ``stream:raise`` fault, which simulates exactly that) flips the
        request's CancelToken instead of decoding on for a dead socket; the
        scheduler releases the row's slot at the next chunk boundary. A
        deadline expiry ends the stream with finish_reason "timeout".

        Disaggregation reuse: ``source`` (a callable taking the
        CancelToken, returning a burst iterator) swaps in the import-admit
        decode of a migrated row, and ``carried`` prepends the tokens the
        exporting replica already emitted — the client's stream is the
        solo stream whichever replica decoded which half.

        Mid-stream failover: ``ckpt_every`` > 0 serializes each
        ``("ckpt", snapshot)`` marker the scheduler interleaves into one
        in-band ``event: dllama-ckpt`` control frame — the snapshot plus
        THIS writer's rendering state (emitted byte count, incremental
        UTF-8 decoder state, pending-token/render counters, the response
        ``base`` identity, the detector's scanback) — which the router
        strips into its checkpoint store; clients talking to the replica
        directly never request checkpoints and never see the frames.
        ``resume_state`` is the other half: /v1/kv/resume rehydrates that
        rendering state so the continued stream's bytes are EXACTLY what
        the dead replica would have written, letting the router splice by
        byte offset alone."""
        st = self.state
        tok = st.tokenizer
        cancel = cancel if cancel is not None else CancelToken()
        self._send_sse_headers(extra=extra_headers)

        client_gone = False
        #: client-visible SSE bytes written so far — checkpoint control
        #: frames excluded, so the count matches what the ROUTER forwards
        #: and the resume splice is pure byte arithmetic
        bytes_emitted = 0

        def emit_frame(frame: bytes, fire: bool = True) -> None:
            nonlocal client_gone, bytes_emitted
            if client_gone:
                return
            try:
                if fire:
                    faults.fire("stream")
                self.wfile.write(frame)
                self.wfile.flush()
                if fire:  # ckpt control frames are stripped by the
                    #       router, so they never count toward the
                    #       client-visible splice offset
                    bytes_emitted += len(frame)
            except (BrokenPipeError, ConnectionResetError,
                    faults.FaultInjected):
                st._m_sse_disconnect.inc()
                client_gone = True
                cancel.cancel("client disconnected mid-stream")

        def emit_chunk(delta: dict, finish=None) -> None:
            chunk = dict(base, object="chat.completion.chunk",
                         choices=[{"index": 0, "delta": delta,
                                   "finish_reason": finish}])
            emit_frame(b"data: " + json.dumps(chunk).encode() + b"\n\n")

        utf8 = codecs.getincrementaldecoder("utf-8")("replace")
        if resume_state is not None:
            # continue the dead replica's stream mid-sentence: same byte
            # position, same half-decoded UTF-8 tail, same pending token —
            # and NO role preamble (the client got it long ago)
            bytes_emitted = int(resume_state["bytes"])
            utf8.setstate((bytes.fromhex(resume_state["utf8"][0]),
                           int(resume_state["utf8"][1])))
            prev = int(resume_state["prev"])
            n_generated = int(resume_state["n_generated"])
        else:
            emit_chunk({"role": "assistant"})
            prev = prompt_tokens[-1]
            n_generated = 0
        stop_ids = st.stop_token_ids()
        finish_reason = "length"

        def emit_ckpt(snap: dict) -> None:
            # at a chunk boundary the writer is exactly between SSE
            # events, so bytes_emitted IS the splice point. A failed
            # serialize is a skipped checkpoint, never a stream error.
            try:
                ustate = utf8.getstate()
                payload = kv_transfer.encode_snapshot(
                    snap, prompt_tokens, mode=ckpt_wire,
                    extra={"resume": {
                        "base": base, "bytes": bytes_emitted,
                        "utf8": [ustate[0].hex(), int(ustate[1])],
                        "prev": prev, "n_generated": n_generated,
                        "request_id": self._rid}},
                    stop_state=(detector.state() if detector is not None
                                else None))
            except Exception:  # noqa: BLE001
                st._m_ckpt_writes.inc(outcome="error")
                return
            emit_frame(_SSE_CKPT_PREFIX
                       + str(bytes_emitted).encode() + b" "
                       + base64.b64encode(payload) + b"\n\n", fire=False)

        try:
            bursts = (source(cancel) if source is not None
                      else st.batcher.submit_stream(
                          prompt_tokens, max_tokens, sampler,
                          deadline=deadline, cancel=cancel, trace=trace,
                          ckpt_every=ckpt_every,
                          slo_class=getattr(self, "_slo_class",
                                            "interactive")))
            if carried:
                bursts = itertools.chain([list(carried)], bursts)
            for burst in bursts:
                if isinstance(burst, tuple) and burst[0] == "ckpt":
                    emit_ckpt(burst[1])
                    continue
                parts = []
                stopped = False
                for t in burst:
                    n_generated += 1
                    if t in stop_ids:
                        stopped = True
                        break
                    piece = utf8.decode(tok.decode_piece(prev, t))
                    prev = t
                    if detector is not None:
                        out, hit = detector.feed(piece)
                        if out:
                            parts.append(out)
                        if hit:
                            stopped = True
                            break
                    else:
                        parts.append(piece)
                text = "".join(parts)
                if text:
                    emit_chunk({"content": text})
                if stopped:
                    finish_reason = "stop"
                    # a stop-STRING trip leaves the pool row live: cancel
                    # so the scheduler frees its slot at the next chunk
                    # boundary instead of decoding to budget
                    cancel.cancel("stop string hit mid-stream")
                    break
                if client_gone:
                    break  # cancel is set; the scheduler reaps the row at
                    # its next chunk boundary — stop consuming now
        except DeadlineExceeded as e:
            emit_chunk({"content": f"\n[error: {e}]"})
            finish_reason = "timeout"
        except NumericHealthError as e:
            # quarantined row: what was streamed before the blowup stands
            # (those chunks were finite); the stream ends with
            # finish_reason "error" so the client knows not to trust more
            emit_chunk({"content": f"\n[error: {e}]"})
            finish_reason = "error"
        except RuntimeError as e:
            emit_chunk({"content": f"\n[error: {e}]"})
        tail = utf8.decode(b"", True)
        if detector is not None and not detector.stopped:
            tail = detector.flush() + tail
        if tail:
            emit_chunk({"content": tail})
        emit_chunk({}, finish=finish_reason)
        if trace is not None:
            trace.finish_reason = finish_reason
            trace.tokens_out = n_generated
        if not client_gone:
            try:
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass  # client closed between the last chunk and [DONE]
        self.close_connection = True

    def _handle_completions(self, req: dict, trace: RequestTrace) -> None:
        st = self.state
        messages = req.get("messages")
        if not isinstance(messages, list) or not messages:
            self._error(400, "messages must be a non-empty list")
            return
        for m in messages:
            if not isinstance(m, dict) or "role" not in m or "content" not in m:
                self._error(400, "each message needs role and content")
                return

        try:
            sampler = SamplerConfig(
                temperature=float(req.get("temperature", st.default_sampler.temperature)),
                topp=float(req.get("top_p", st.default_sampler.topp)),
                seed=int(req["seed"]) if req.get("seed") is not None
                else st.default_seed if st.default_seed is not None
                else int(time.time_ns() % (1 << 31)),
            )
            stops = req.get("stop") or []
            if isinstance(stops, str):
                stops = [stops]
            if not (isinstance(stops, list) and all(isinstance(s, str) for s in stops)):
                raise ValueError("stop must be a string or list of strings")
            stream = bool(req.get("stream", False))
            mt = req.get("max_tokens")
            max_tokens = None if mt is None else max(1, int(mt))
            n_choices = max(1, int(req.get("n", 1) or 1))
        except (TypeError, ValueError) as e:
            self._error(400, f"bad request parameter: {e}")
            return
        if n_choices > st.batch_max:
            self._error(400, f"n is capped at {st.batch_max} (--batch-max: "
                             "each choice holds a full KV cache in device "
                             "memory)")
            return
        if n_choices > 1 and stream:
            self._error(400, "n > 1 with stream is not supported")
            return

        tok = st.tokenizer
        prompt = st.build_prompt(messages)
        prompt_tokens = tok.encode(prompt, add_bos=True)
        trace.tokens_in = len(prompt_tokens)
        trace.prompt_sha = observability.prompt_digest(prompt)
        if st.log_prompts:
            trace.prompt_text = prompt
        if st.batcher is not None:
            trace.queue_depth = st.batcher.queue_depth()
        room = st.cfg.seq_len - len(prompt_tokens)
        if room <= 0:
            self._error(400, f"prompt of {len(prompt_tokens)} tokens exceeds "
                             f"the {st.cfg.seq_len}-token context")
            return
        max_tokens = room if max_tokens is None else min(max_tokens, room)
        # wall-clock budget counted from HERE (admission), not from first
        # token: queue time burns budget too, by design. Class-scoped: a
        # lane's configured deadline outranks the global --request-timeout
        deadline = self._start_deadline()

        cid = _completion_id()
        created = int(time.time())
        base = {"id": cid, "object": "chat.completion", "created": created,
                "model": st.model_name}

        if n_choices > 1:
            # n samples of one prompt decode as ONE batch: the shared
            # prefix prefills once, every step streams the weights once for
            # all n rows (generate_batch); choice i runs its own chain at
            # seed+i — bit-identical to a solo request with that seed
            try:
                prompts, row_steps = padded_batch(
                    [list(prompt_tokens)] * n_choices,
                    [max_tokens] * n_choices)
                samplers = [
                    SamplerConfig(temperature=sampler.temperature,
                                  topp=sampler.topp, seed=sampler.seed + i)
                    for i in range(n_choices)
                ] + [SamplerConfig(temperature=0.0, seed=0)] * (
                    len(prompts) - n_choices)
                with st.lock:
                    trace.mark_start("n_batch")
                    rows = st.engine.generate_batch(
                        prompts, max_tokens,
                        samplers=samplers, stop_tokens=st.stop_token_ids(),
                        row_steps=row_steps,
                    )[:n_choices]
                    trace.mark_prefill(
                        getattr(st.engine, "prefill_ms", 0.0) or 0.0)
            except Exception as e:  # noqa: BLE001
                self._error(500, f"batched n-sampling failed: {e!r}")
                return
            row_health = getattr(st.engine, "row_health", None)
            choices, total = [], 0
            for idx, row in enumerate(rows):
                text, finish, n_gen = decode_token_row(
                    tok, prompt_tokens[-1], row[:max_tokens],
                    st.stop_token_ids(), stops)
                total += n_gen
                if row_health is not None and not row_health[idx]:
                    # this choice's logits went non-finite mid-decode: its
                    # text is untrustworthy from the blowup point — flag it
                    # instead of failing the healthy sibling choices
                    finish = "error"
                choices.append({
                    "index": idx,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish,
                })
            trace.tokens_out = total
            trace.finish_reason = choices[0]["finish_reason"]
            self._json(200, dict(base, choices=choices, usage={
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": total,
                "total_tokens": len(prompt_tokens) + total,
            }))
            return

        # mid-stream failover: the router opts a stream into periodic
        # checkpointing with X-Dllama-Ckpt. Only a streaming request in
        # the PAGED batcher pool can checkpoint (export_row needs pages);
        # anything else ignores the header and degrades to the router's
        # no-checkpoint fallback (clean SSE error on death).
        ckpt_every, ckpt_wire = self._ckpt_request()
        if not (stream and st.batcher is not None
                and st.batcher.kv_pages > 0):
            ckpt_every = 0

        if (st.batcher is not None and (not stops or ckpt_every > 0)
                and not st.has_prefix_session(prompt_tokens)):
            # stop STRINGS stay on the solo path: its host loop aborts at
            # the string, while a batch would decode the row's whole budget
            # on device before the host truncates. EXCEPT when the router
            # asked for checkpoints — resumability needs the paged pool,
            # so a checkpointing stop-string stream runs batched with an
            # in-handler StopDetector (its scanback state rides every
            # checkpoint), trading the early-abort for failover coverage.
            # A prompt that EXTENDS a cached conversation also stays solo:
            # the batch path skips the prefix cache, and re-prefilling a
            # growing history every turn would regress multi-turn latency
            # with zero concurrency.
            # Everything else — greedy or sampled, streaming or not —
            # merges into one batched decode; every row runs its own
            # sampler chain, so tokens are bit-identical to the solo path
            # for the same SamplerConfig. On a --spec-draft server an
            # all-greedy batch (streaming included — per-launch bursts)
            # runs the BATCHED speculative verify (Batcher._serve);
            # singletons speculate on the solo path either way.
            if stream:
                self._stream_batched(base, sampler, prompt_tokens, max_tokens,
                                     deadline=deadline, trace=trace,
                                     detector=(StopDetector(stops)
                                               if stops else None),
                                     ckpt_every=ckpt_every,
                                     ckpt_wire=ckpt_wire)
            else:
                try:
                    row = st.batcher.submit(prompt_tokens, max_tokens, sampler,
                                            deadline=deadline, trace=trace,
                                            slo_class=getattr(
                                                self, "_slo_class",
                                                "interactive"))
                except LifecycleError:
                    raise  # do_POST speaks its status (504/503) — must
                    # outrank the RuntimeError catch below (LifecycleError
                    # IS a RuntimeError)
                except RuntimeError as e:
                    # one poisoned batch must not reset K connections: every
                    # waiter gets its own 500
                    self._error(500, str(e))
                    return
                text, finish_reason, n_generated = decode_token_row(
                    tok, prompt_tokens[-1], row, st.stop_token_ids(), stops)
                trace.tokens_out = n_generated
                trace.finish_reason = finish_reason
                self._json(200, dict(base, choices=[{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish_reason,
                }], usage={
                    "prompt_tokens": len(prompt_tokens),
                    "completion_tokens": n_generated,
                    "total_tokens": len(prompt_tokens) + n_generated,
                }))
            return

        if stream:
            self._send_sse_headers()

        detector = StopDetector(stops)
        text_parts: list = []
        finish_reason = "length"
        n_generated = 0
        client_gone = False

        def emit_chunk(delta: dict, finish=None) -> None:
            nonlocal client_gone
            if client_gone:
                return
            try:
                faults.fire("stream")
                chunk = dict(base, object="chat.completion.chunk",
                             choices=[{"index": 0, "delta": delta,
                                       "finish_reason": finish}])
                self.wfile.write(b"data: " + json.dumps(chunk).encode()
                                 + b"\n\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError,
                    faults.FaultInjected):
                # dead socket: stop decoding at the next token boundary but
                # DON'T raise out of the locked loop — the prefix session
                # still gets stored (the conversation may reconnect)
                st._m_sse_disconnect.inc()
                client_gone = True

        if stream:
            emit_chunk({"role": "assistant"})

        # incremental UTF-8: a multi-byte character split across byte-fallback
        # tokens must not be decoded per piece (that would emit U+FFFD pairs)
        utf8 = codecs.getincrementaldecoder("utf-8")("replace")
        interrupted = None  # "timeout" when the deadline ends the decode
        health_err = None  # NumericHealthError when the watchdog trips
        with st.lock:
            trace.mark_start("solo")
            prev = prompt_tokens[-1]
            stop_ids = st.stop_token_ids()
            session, feed_tokens = st.take_prefix_session(prompt_tokens)
            history = list(prompt_tokens)
            stream_iter = st.open_stream(prompt_tokens, feed_tokens, session,
                                         max_tokens, sampler)
            try:
                for tok_id, _stats in stream_iter:
                    n_generated += 1
                    trace.mark_token()
                    history.append(tok_id)
                    if tok_id in stop_ids:
                        finish_reason = "stop"
                        break
                    piece = utf8.decode(tok.decode_piece(prev, tok_id))
                    prev = tok_id
                    out, hit_stop = detector.feed(piece)
                    if out:
                        text_parts.append(out)
                        if stream:
                            emit_chunk({"content": out})
                    if hit_stop:
                        finish_reason = "stop"
                        break
                    if client_gone:
                        break  # abandon the generator at a token boundary
                    if deadline is not None and deadline.expired():
                        interrupted = "timeout"
                        break
            except NumericHealthError as e:
                # the watchdog tripped: everything emitted so far was
                # finite, but the session's KV state is poisoned — do NOT
                # cache it for the next turn of this conversation
                health_err = e
            trace.mark_prefill(getattr(st.engine, "prefill_ms", 0.0) or 0.0)
            if health_err is None:
                st.store_prefix_session(history, st.engine.final_session)

        trace.tokens_out = n_generated
        if health_err is not None:
            trace.finish_reason = "error"
            if not stream:
                self._error(500, f"decode failed: {health_err}")
                return
            emit_chunk({"content": f"\n[error: {health_err}]"})
            finish_reason = "error"
        elif interrupted == "timeout":
            if not stream:
                raise deadline.error()  # -> 504 via do_POST
            emit_chunk({"content": f"\n[error: {deadline.error()}]"})
            finish_reason = "timeout"
        elif not detector.stopped:
            # flush text withheld as a possible stop-string prefix — on EOS or
            # length it is legitimate output, only a stop-string hit eats it —
            # plus the replacement char for any dangling incomplete UTF-8 bytes
            tail = detector.flush() + utf8.decode(b"", True)
            if tail:
                text_parts.append(tail)
                if stream:
                    emit_chunk({"content": tail})

        trace.finish_reason = finish_reason
        if stream:
            emit_chunk({}, finish=finish_reason)
            if not client_gone:
                try:
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client closed between the last chunk and [DONE]
            self.close_connection = True
        else:
            self._json(200, dict(base, choices=[{
                "index": 0,
                "message": {"role": "assistant", "content": "".join(text_parts)},
                "finish_reason": finish_reason,
            }], usage={
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": n_generated,
                "total_tokens": len(prompt_tokens) + n_generated,
            }))


    # -- disaggregated serving (role-aware fleet) -------------------------
    def _finished_row_response(self, base: dict, prompt_tokens: list,
                               row: list, stream: bool, trace,
                               stops: list = None) -> None:
        """Answer a COMPLETE token row in the client's requested shape —
        the prefill hop uses this when the row finished inside its first
        chunk (nothing migrated), and the import hop for its final
        non-streaming answer. SSE here is a replay of finished tokens,
        not a live stream; the router relays the bytes verbatim."""
        st = self.state
        text, finish, n_gen = decode_token_row(
            st.tokenizer, prompt_tokens[-1], row, st.stop_token_ids(),
            stops or [])
        trace.tokens_out = n_gen
        trace.finish_reason = finish
        if not stream:
            self._json(200, dict(base, choices=[{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish,
            }], usage={
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": n_gen,
                "total_tokens": len(prompt_tokens) + n_gen,
            }))
            return
        self._send_sse_headers()
        try:
            for delta, fin in ((({"role": "assistant"}), None),
                               (({"content": text} if text else None), None),
                               ({}, finish)):
                if delta is None and fin is None:
                    continue
                chunk = dict(base, object="chat.completion.chunk",
                             choices=[{"index": 0, "delta": delta or {},
                                       "finish_reason": fin}])
                self.wfile.write(b"data: " + json.dumps(chunk).encode()
                                 + b"\n\n")
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client vanished; nothing is decoding on its behalf
        self.close_connection = True

    def _handle_prefill(self, req: dict, trace: RequestTrace) -> None:
        """POST /v1/prefill — hop 1 of a disaggregated request: admit the
        prompt into the paged pool, decode its FIRST chunk here, then
        export the row (pages + carried sampler-chain state) as a framed
        KV stream for the router to deliver to a decode replica. A row
        that finishes inside that first chunk answers the client's shape
        directly (nothing to migrate). Body = the chat-completions JSON
        plus optional "kv_wire" ("f32" bit-exact, default / "q80"
        block-quantized)."""
        st = self.state
        if st.batcher is None or st.batcher.kv_pages <= 0:
            self._error(400, "disaggregated prefill needs --batch-window "
                             "> 0 and --kv-pages (paged KV pool)")
            return
        messages = req.get("messages")
        if not isinstance(messages, list) or not messages:
            self._error(400, "messages must be a non-empty list")
            return
        for m in messages:
            if not isinstance(m, dict) or "role" not in m \
                    or "content" not in m:
                self._error(400, "each message needs role and content")
                return
        try:
            sampler = SamplerConfig(
                temperature=float(req.get(
                    "temperature", st.default_sampler.temperature)),
                topp=float(req.get("top_p", st.default_sampler.topp)),
                seed=int(req["seed"]) if req.get("seed") is not None
                else st.default_seed if st.default_seed is not None
                else int(time.time_ns() % (1 << 31)),
            )
            stream = bool(req.get("stream", False))
            mt = req.get("max_tokens")
            max_tokens = None if mt is None else max(1, int(mt))
            wire = str(req.get("kv_wire", "f32"))
        except (TypeError, ValueError) as e:
            self._error(400, f"bad request parameter: {e}")
            return
        stops = req.get("stop") or []
        if isinstance(stops, str):
            stops = [stops]
        if not (isinstance(stops, list)
                and all(isinstance(s, str) for s in stops)):
            self._error(400, "stop must be a string or list of strings")
            return
        if int(req.get("n", 1) or 1) != 1:
            self._error(400, "n > 1 cannot be served disaggregated")
            return
        if wire not in kv_transfer.WIRE_MODES:
            self._error(400, f"unknown kv_wire {wire!r} "
                             f"(know {kv_transfer.WIRE_MODES})")
            return
        tok = st.tokenizer
        prompt = st.build_prompt(messages)
        prompt_tokens = tok.encode(prompt, add_bos=True)
        trace.tokens_in = len(prompt_tokens)
        trace.prompt_sha = observability.prompt_digest(prompt)
        room = st.cfg.seq_len - len(prompt_tokens)
        if room <= 0:
            self._error(400, f"prompt of {len(prompt_tokens)} tokens "
                             f"exceeds the {st.cfg.seq_len}-token context")
            return
        max_tokens = room if max_tokens is None else min(max_tokens, room)
        deadline = self._start_deadline()
        base = {"id": _completion_id(), "object": "chat.completion",
                "created": int(time.time()), "model": st.model_name}
        try:
            snap, emitted = st.batcher.submit_prefill(
                prompt_tokens, max_tokens, sampler, deadline=deadline,
                trace=trace)
        except LifecycleError:
            raise  # do_POST speaks its status
        except RuntimeError as e:
            self._error(500, f"prefill-export failed: {e}")
            return
        if snap is None:
            # finished inside the first chunk: answer the client directly
            self._finished_row_response(base, prompt_tokens, emitted,
                                        stream, trace, stops=stops)
            return
        # stop STRINGS migrate with the row: the exporter decoded only
        # token ids (never text), so a FRESH detector state travels in
        # the v2 header and the importer scans carried + fresh text
        # through it — the same scanback the solo path would have run
        payload = kv_transfer.encode_snapshot(
            snap, prompt_tokens, mode=wire,
            extra={"stream": stream,
                   "emitted_tokens": [int(t) for t in emitted],
                   "request_id": self._rid},
            stop_state=({"stops": stops, "hold": "", "stopped": False}
                        if stops else None))
        st._m_kv_bytes.inc(len(payload), direction="out")
        st._m_kv_pages.inc(float(snap["n_blocks"]), direction="out")
        trace.tokens_out = len(emitted)
        trace.finish_reason = "migrated"
        self.send_response(200)
        self.send_header("Content-Type", kv_transfer.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(payload)))
        self.send_header(HDR_REQUEST_ID, self._rid)
        self.send_header(HDR_SERVER_TIMING, self._server_timing())
        self.end_headers()
        self._count(200)
        self.wfile.write(payload)

    def _handle_kv_import(self, body: bytes, trace: RequestTrace) -> None:
        """POST /v1/kv/import — hop 2: decode the framed page stream
        FULLY (a torn stream is rejected before the pool is touched),
        admit the row warm, and serve the remaining decode in the
        client's shape — carried tokens the exporter already emitted are
        prepended, so the client sees one seamless stream."""
        st = self.state
        if st.batcher is None or st.batcher.kv_pages <= 0:
            self._error(400, "KV import needs --batch-window > 0 and "
                             "--kv-pages (paged KV pool)")
            return
        try:
            snap = kv_transfer.decode_snapshot(body)
        except kv_transfer.TransferError as e:
            st._m_kv_imports.inc(outcome="rejected")
            self._error(422, f"rejected KV stream: {e}")
            return
        st._m_kv_bytes.inc(len(body), direction="in")
        st._m_kv_pages.inc(float(snap["n_blocks"]), direction="in")
        extra = snap.get("extra") or {}
        stream = bool(extra.get("stream"))
        carried = [int(t) for t in extra.get("emitted_tokens") or []]
        prompt_tokens = list(snap["prompt"])
        trace.tokens_in = len(prompt_tokens)
        # a v2 stream migrates its stop-string scanback; the carried
        # tokens' text runs through the same detector before any fresh
        # decode, so the stop fires exactly where the solo path's would
        stop_state = snap.get("stop_state")
        detector = (StopDetector.from_state(stop_state)
                    if stop_state else None)
        deadline = self._start_deadline()
        base = {"id": _completion_id(), "object": "chat.completion",
                "created": int(time.time()), "model": st.model_name}
        if stream:
            sampler = SamplerConfig(temperature=float(snap["temp"]),
                                    topp=float(snap["topp"]), seed=0)
            # a migrated stream can opt into checkpointing too — a decode
            # replica death after a migration is just another failover
            ckpt_every, ckpt_wire = self._ckpt_request()
            # pre-pull the FIRST burst before any SSE byte leaves: a row
            # the pool can't admit must answer 5xx (the router's fallback
            # cue), not a 200 stream that dies mid-flight
            cancel = CancelToken()
            gen = st.batcher.submit_import_stream(
                snap, deadline=deadline, cancel=cancel, trace=trace,
                ckpt_every=ckpt_every)
            try:
                first = next(gen, None)
            except LifecycleError:
                raise
            except RuntimeError as e:
                self._error(503, f"KV import failed: {e}")
                return
            self._stream_batched(
                base, sampler, prompt_tokens,
                int(snap["budget"]) - int(snap["emitted"]),
                deadline=deadline, trace=trace, carried=carried,
                source=lambda _c: (itertools.chain([first], gen)
                                   if first is not None else gen),
                cancel=cancel, detector=detector,
                ckpt_every=ckpt_every, ckpt_wire=ckpt_wire)
            return
        try:
            fresh = st.batcher.submit_import(snap, deadline=deadline,
                                             trace=trace)
        except LifecycleError:
            raise
        except RuntimeError as e:
            # includes "no free KV pages": the router's cue to fall back
            self._error(503, f"KV import failed: {e}")
            return
        self._finished_row_response(
            base, prompt_tokens, carried + fresh, stream, trace,
            stops=(list(stop_state["stops"]) if stop_state else None))

    def _handle_kv_resume(self, body: bytes, trace: RequestTrace) -> None:
        """POST /v1/kv/resume — mid-stream failover: decode a dead
        sibling's checkpoint FULLY, admit the row warm, rehydrate the
        dead writer's rendering state (byte offset, half-decoded UTF-8
        tail, pending token, stop-string scanback) and continue the SSE
        stream from the NEXT token. The continued bytes are EXACTLY what
        the dead replica would have written, so the router splices by
        discarding the prefix the client already holds — echoed in the
        X-Dllama-Resume-Offset header before any SSE byte leaves. A row
        this pool can't admit answers 5xx (the router tries the next
        sibling, then degrades to the clean SSE error termination)."""
        st = self.state
        if st.batcher is None or st.batcher.kv_pages <= 0:
            self._error(400, "KV resume needs --batch-window > 0 and "
                             "--kv-pages (paged KV pool)")
            return
        try:
            snap = kv_transfer.decode_snapshot(body)
        except kv_transfer.TransferError as e:
            st._m_kv_imports.inc(outcome="rejected")
            self._error(422, f"rejected KV stream: {e}")
            return
        resume = (snap.get("extra") or {}).get("resume")
        try:
            base = dict(resume["base"])
            bytes.fromhex(str(resume["utf8"][0]))  # validated BEFORE the
            # SSE headers go out — a torn hex tail must 422, not crash a
            # 200 stream
            resume_state = {"bytes": int(resume["bytes"]),
                            "utf8": [str(resume["utf8"][0]),
                                     int(resume["utf8"][1])],
                            "prev": int(resume["prev"]),
                            "n_generated": int(resume["n_generated"])}
        except (KeyError, IndexError, TypeError, ValueError) as e:
            st._m_kv_imports.inc(outcome="rejected")
            self._error(422, f"not a resumable checkpoint: {e}")
            return
        st._m_kv_bytes.inc(len(body), direction="in")
        st._m_kv_pages.inc(float(snap["n_blocks"]), direction="in")
        prompt_tokens = list(snap["prompt"])
        trace.tokens_in = len(prompt_tokens)
        detector = (StopDetector.from_state(snap["stop_state"])
                    if snap.get("stop_state") else None)
        # the resumed stream keeps checkpointing at the router's cadence:
        # a SECOND death mid-resume is just another resume
        ckpt_every, ckpt_wire = self._ckpt_request()
        deadline = self._start_deadline()
        sampler = SamplerConfig(temperature=float(snap["temp"]),
                                topp=float(snap["topp"]), seed=0)
        cancel = CancelToken()
        gen = st.batcher.submit_import_stream(
            snap, deadline=deadline, cancel=cancel, trace=trace,
            ckpt_every=ckpt_every)
        try:
            first = next(gen, None)
        except LifecycleError:
            raise
        except RuntimeError as e:
            # includes "no free KV pages" and "row already finished"
            self._error(503, f"KV resume failed: {e}")
            return
        self._stream_batched(
            base, sampler, prompt_tokens,
            int(snap["budget"]) - int(snap["emitted"]),
            deadline=deadline, trace=trace,
            source=lambda _c: (itertools.chain([first], gen)
                               if first is not None else gen),
            cancel=cancel, detector=detector,
            ckpt_every=ckpt_every, ckpt_wire=ckpt_wire,
            resume_state=resume_state,
            extra_headers={HDR_RESUME_OFFSET:
                           str(resume_state["bytes"])})


def create_server(state: ServerState, host: str = "0.0.0.0", port: int = 9990):
    handler = type("Handler", (OpenAIHandler,), {"state": state})
    srv = ThreadingHTTPServer((host, port), handler)
    # identity binds to the ACTUAL port (port=0 tests get the kernel's
    # pick): port names the replica across restarts, the nonce names this
    # generation of it
    bound = srv.server_address[1]
    state.replica_id = f"{bound}-{state.start_nonce}"
    state.flight.process = f"replica-{bound}"
    # history/alerts start with the listener: a bare ServerState (unit
    # tests, bench replays) stays thread-free, a serving one remembers
    state.sampler.start()
    return srv


def drain_and_shutdown(state: ServerState, srv, drain_timeout_s: float) -> bool:
    """SIGTERM graceful drain: stop admitting (new requests 503 at the
    gate, /ready flips 503 so the balancer stops routing here), wait up to
    ``drain_timeout_s`` for in-flight requests, then stop the listener.
    Returns True when the drain completed with nothing in flight (a False
    means live requests were cut off at the timeout)."""
    state.flight.dump("sigterm")  # the shutdown's black box, written FIRST:
    # if the drain itself wedges, the ring already shows what was in flight
    state.begin_drain()
    idle = state.gate.wait_idle(drain_timeout_s)
    state.sampler.stop()
    srv.shutdown()
    return idle


def serve(args) -> None:
    """Start the server from parsed CLI args (the ``serve`` mode of
    ``dllama_tpu.cli``, analogous to launching the reference's dllama-api
    binary with the same flag set, `dllama-api.cpp:357-362`)."""
    import signal

    from dllama_tpu.cli import load_engine, write_pid_file

    engine, tok, cfg = load_engine(args)
    state = ServerState(
        engine, tok, cfg,
        model_name=args.model.rsplit("/", 1)[-1],
        template=args.chat_template,
        # default_sampler carries only temperature/topp; the per-request seed
        # comes from default_seed (single source of truth)
        default_sampler=SamplerConfig(temperature=args.temperature, topp=args.topp),
        default_seed=args.seed,
        spec_draft=getattr(args, "spec_draft", 0),
        session_cache=getattr(args, "session_cache", 2),
        batch_window_ms=getattr(args, "batch_window", 0.0),
        batch_max=getattr(args, "batch_max", 8),
        batch_chunk=getattr(args, "batch_chunk", 8),
        prefill_chunk=getattr(args, "prefill_chunk", -1),
        kv_buckets=getattr(args, "kv_buckets", 1),
        kv_bucket_min=getattr(args, "kv_bucket_min", 0),
        kv_pages=getattr(args, "kv_pages", 0),
        request_timeout=getattr(args, "request_timeout", 0.0),
        queue_depth=getattr(args, "queue_depth", 64),
        log_json=getattr(args, "log_json", False),
        log_prompts=getattr(args, "log_prompts", False),
        role=getattr(args, "role", "both") or "both",
        ckpt_interval=getattr(args, "ckpt_interval", 32),
        slo_classes=getattr(args, "slo_classes", None),
        ts_interval=getattr(args, "ts_interval", 1.0),
        burn_short=getattr(args, "burn_short", 60.0),
        burn_long=getattr(args, "burn_long", 300.0),
    )
    srv = create_server(state, host=args.host, port=args.port)
    # label this pid's track group in a merged fleet trace (no-op when
    # DLLAMA_TRACE is unset)
    observability.emit_process_name(f"replica:{args.port}")
    pid_path = getattr(args, "pid_file", None)
    if pid_path:
        write_pid_file(pid_path)
    drain_timeout_s = getattr(args, "drain_timeout", 30.0)

    def _on_sigterm(_signum, _frame):
        # drain OFF the signal frame: srv.shutdown() blocks until
        # serve_forever exits, and wait_idle may sleep for the full drain
        # window — neither belongs in a signal handler
        print(f"⛔ SIGTERM: draining (up to {drain_timeout_s:.0f}s) ...")
        threading.Thread(
            target=drain_and_shutdown, args=(state, srv, drain_timeout_s),
            daemon=True, name="dllama-drain").start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded/test use): no signal hook
    print(f"📡 listening on {args.host}:{args.port} "
          "(POST /v1/chat/completions, GET /v1/models /metrics /stats)")
    try:
        srv.serve_forever()
    finally:
        if pid_path:
            try:
                os.remove(pid_path)
            except OSError:
                pass  # pid file already gone (drain path) or never written


def main(argv=None) -> None:
    import sys

    from dllama_tpu.cli import build_parser

    if argv is None:
        argv = sys.argv[1:]
    serve(build_parser().parse_args(["serve"] + list(argv)))


if __name__ == "__main__":
    main()
