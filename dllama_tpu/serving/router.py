"""Fleet front door: a stateless HTTP router over N ``dllama-api`` replicas.

Every lifecycle/serving PR so far (429/503/504 semantics, /health vs /ready,
SIGTERM drain, X-Request-Id, the radix prefix cache) was designed so a fleet
of identical replicas could sit behind a load balancer; this module IS that
balancer, stdlib-only like the rest of serving/. It proxies the OpenAI
surface (``/v1/chat/completions`` incl. SSE streaming passthrough,
``/v1/models``) and keeps no request state of its own — kill the router,
restart it, and the fleet picture rebuilds from one probe round.

Routing policy, in order:

* **prefix affinity** — multi-turn traffic should land where its KV pages
  are warm. The router has no tokenizer, so affinity keys on the canonical
  *byte* stream of the messages array, hashed in cumulative block-aligned
  prefixes (``--affinity-block`` bytes per block): turn N+1 carries turn N's
  rendered conversation as a byte prefix, so its longest matching block
  hash points at the replica whose radix cache already holds those pages.
  A saturated affinity target (slots full AND queue backed up) falls back
  to least-load — a warm cache never justifies queueing behind it.
* **weighted least-load** — scored from the occupancy/queue-depth/kv-page
  fields each replica publishes on ``/ready`` (one cheap probe carries the
  whole picture), plus the router's own live in-flight count per replica
  (the probe snapshot is up to a probe interval stale; in-flight is not).
* **failover** — connect-phase failures and 503s (a draining or
  mid-restart replica) retry on the next-best replica under
  ``--retry-budget``; 429 (fleet at capacity) and 504 (deadline) pass
  through untouched — retrying those would amplify overload or burn a
  client's remaining deadline. Once bytes have streamed to the client,
  nothing retries.

Replica health is judged twice: an active ``/ready`` probe loop (drain
flips a replica out of rotation within one probe interval) and passive
circuit-breaking on data-path connect errors (exponential backoff, closed
again by the next successful probe). Either alone has a blind spot — the
probe is periodic, the data path only sees replicas it already picked.

The data plane is a stdlib ``selectors`` event loop (:mod:`evloop`): one
thread, non-blocking sockets, one coroutine per client connection — 10k
concurrent SSE streams fit in one process because an idle stream costs a
parked generator, not an OS thread. The loop structure is what makes the
robustness machinery expressible: per-edge deadlines (``--header-timeout``
kills slow-loris clients, connect/first-byte budgets bound each upstream
hop, the ``--stall-timeout`` inter-byte budget turns a GRAY upstream —
accepted socket, then silence mid-SSE — into a checkpoint-resume with
``outcome=stall``), slow-client backpressure (the relay holds one chunk at
a time, so a client that stops draining pauses its upstream read instead
of growing router RSS, and is hard-killed past ``--client-stall-timeout``),
and ``--max-conns`` admission (new connections shed with a canned 503 +
Retry-After at accept time, BEFORE any state is allocated). Control-plane
work that legitimately blocks — probes, federation scrapes — runs on
worker threads, never the loop.

The router serves its own ``/health``, ``/ready``, ``/metrics`` and
``/stats`` (aggregating per-replica state) and generates/propagates
``X-Request-Id`` across the hop so a trace correlates end-to-end. Fault
seams ``route_pick``, ``proxy_upstream``, ``probe``, ``federate_scrape``,
``conn_accept``, ``relay_stall`` and ``client_write`` are wired through
``faults.SITES``; injected failures take the same retry/circuit/shed
paths as real ones.

Fleet observability (this is the stitching half of observability.py):

* every proxied request carries ``X-Dllama-Parent-Span: <pid>:<span>``
  upstream; the replica parents its RequestTrace under it and the router
  emits the matching flow arrow, so one merged Perfetto file shows the
  router's proxy/connect/stream spans and the replica's queue/prefill/
  decode spans on a common timeline. The probe loop doubles as a clock
  sync: the replica stamps ``/ready`` with its monotonic-epoch time, the
  router subtracts half the probe RTT, and the per-replica offset feeds
  ``merge_trace_parts`` at fleet shutdown so spans nest despite skew.
* ``GET /metrics/fleet`` scrapes every in-rotation replica's /metrics and
  merges the expositions under a ``replica`` label (counters sum, gauges
  stay per-replica, histogram buckets merge); a crashed replica's series
  drop out with its circuit, and a failed scrape drops that replica from
  the merge — never the endpoint.
* each replica's ``Server-Timing`` response header splits the router's
  wall time into ``dllama_router_hop_ms{phase=connect|upstream_queue|
  upstream_compute|stream}`` — where a slow request spent its time, per
  hop, without parsing any trace.
* the router keeps its own flight-recorder ring (admits at the replicas,
  upstream errors, replica generation changes) and ``GET /debug/flight``
  returns it together with every replica's ring — the one call a
  postmortem starts from.

Disaggregated serving: when the fleet declares both dedicated ``prefill``
and dedicated ``decode`` replicas (the ``role`` field each publishes on
``/ready``), new chat completions take the migration path instead —
``POST /v1/prefill`` on a prefill replica runs the prompt and the FIRST
decode chunk, then answers with a framed KV page stream
(:mod:`kv_transfer`); the router relays that stream into
``POST /v1/kv/import`` on a decode replica, which admits the row warm and
streams the rest. The ``migrate`` fault seam sits at the decision point,
and EVERY failure along the two hops falls back to normal routing (a full
re-prefill on whatever replica pick() chooses) — a torn transfer is a
performance event, never a client-visible error.

Mid-stream failover: with ``--ckpt-interval`` > 0 every proxied stream
asks its replica (``X-Dllama-Ckpt``) to interleave in-band
``event: dllama-ckpt`` control frames — the row's KV pages + sampler
chain (:mod:`kv_transfer`) plus the SSE writer's exact rendering state,
prefixed with the client-visible byte offset the snapshot describes. The
relay strips those frames into a bounded per-request
:class:`CheckpointStore` (clients never see them) and, when the upstream
dies mid-SSE without ``[DONE]``, picks a sibling, POSTs the checkpoint to
``/v1/kv/resume`` and splices the continued stream into the SAME client
connection, discarding the byte prefix the client already holds — the
bytes are what the dead replica would have written, so the client sees no
repeat and no gap. The ``resume`` fault seam sits at the decision point;
every outcome (ok or any fallback-matrix row) is counted in
``dllama_stream_resume_total{outcome}``, flight-recorded, and closed with
a clean SSE ``error`` event + ``[DONE]`` when resume is exhausted —
never a silent TCP cut.
"""

from __future__ import annotations

import base64
import hashlib
import http.client
import json
import os
import sys
import threading
import time
from collections import OrderedDict

from dllama_tpu import faults, observability
from dllama_tpu.analysis.sanitize import guarded_by, loop_callback
from dllama_tpu.serving import evloop
from dllama_tpu.obsv import Sampler, TimeSeriesStore
from dllama_tpu.obsv.timeseries import parse_window
from dllama_tpu.serving import kv_transfer
from dllama_tpu.serving.lifecycle import LifecycleError, Supervisor
from dllama_tpu.serving.protocol import (HDR_CKPT, HDR_CKPT_WIRE, HDR_CLASS,
                                         HDR_PARENT_SPAN, HDR_REQUEST_ID,
                                         HDR_RESUME_OFFSET,
                                         HDR_SERVER_TIMING, SSE_EVENT_CKPT)

#: the checkpoint control frame's event name as the scanner sees it
_CKPT_EVENT_B = SSE_EVENT_CKPT.encode()

#: longest prompt prefix the affinity index keys on, in blocks — bounds the
#: per-request hashing work and the index growth per conversation
MAX_AFFINITY_BLOCKS = 64

#: least-load score weights: queue depth outranks occupancy (queued work is
#: guaranteed wait; occupied slots may finish any chunk), kv-page pressure
#: is a tiebreaker between equally-busy replicas, and the router's own
#: in-flight count breaks ties between idle replicas *within* one probe
#: interval (it is the only live signal between probes)
W_OCCUPANCY = 1.0
W_QUEUE = 2.0
W_KV = 0.5
W_INFLIGHT = 0.25
#: lane-pressure weight: when the request carries an SLO class, a replica
#: whose matching lane is backed up (lane inflight + lane queue vs the
#: lane's capacity, from /ready's per-class view) scores worse — an
#: interactive turn steers away from the replica drowning in interactive
#: work even when its TOTAL load ties with a sibling's
W_CLASS = 1.5


class NoReplicaAvailable(LifecycleError):
    """No routable replica (all draining, dead, or circuit-open): HTTP 503.

    Carries Retry-After like the in-replica lifecycle rejections — the
    client should back off for roughly one probe interval, after which a
    restarted/undrained replica would be back in rotation."""

    http_status = 503

    def __init__(self, n_replicas: int, n_excluded: int,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"no replica available ({n_replicas} configured, "
            f"{n_excluded} already tried this request)")
        self.retry_after_s = retry_after_s


def canonical_prompt_bytes(messages: list) -> bytes:
    """The affinity hash input: role/content pairs framed with separator
    bytes that cannot appear in JSON string content. Deliberately NOT the
    rendered chat template: the router is template-agnostic, and any stable
    injective encoding works — turn N+1's encoding extends turn N's."""
    parts = []
    for m in messages:
        if not isinstance(m, dict):
            continue
        content = m.get("content", "")
        if not isinstance(content, str):
            # multi-part content arrays hash as their canonical JSON
            content = json.dumps(content, sort_keys=True)
        parts.append(str(m.get("role", "")).encode("utf-8", "replace")
                     + b"\x1f" + content.encode("utf-8", "replace") + b"\x1e")
    return b"".join(parts)


def prefix_hashes(messages: list, block: int) -> list:
    """Cumulative sha256 of each block-aligned prefix of the canonical
    prompt bytes, shortest first. Hash i covers bytes [0, (i+1)*block) —
    cumulative, so two conversations sharing hash i share the whole
    prefix, exactly the property the replica-side radix cache exploits."""
    if block <= 0:
        return []
    data = canonical_prompt_bytes(messages)
    n_blocks = min(len(data) // block, MAX_AFFINITY_BLOCKS)
    h = hashlib.sha256()
    out = []
    for i in range(n_blocks):
        h.update(data[i * block:(i + 1) * block])
        out.append(h.hexdigest())
    return out


def load_score(snap: dict, stale: bool = False,
               slo_class: str = None) -> float:
    """Weighted least-load score for one replica snapshot (lower = better).
    Every term is normalized by the replica's slot count so heterogeneous
    fleets (different --batch-max) compare fairly.

    ``slo_class`` adds the matching lane's pressure (its inflight + queued
    count over its capacity, from the replica's per-class /ready view) so
    classed traffic spreads by LANE load, not just total load. Replicas
    predating the per-class view contribute no lane term — mixed fleets
    keep comparing on the shared terms.

    ``stale`` means the probe snapshot is too old to trust (older than
    twice the probe interval — the probe loop is wedged or the replica is
    slow-walking /ready): score on the router's own live in-flight count
    alone rather than on occupancy/queue/kv numbers frozen at their last
    good values."""
    load = snap.get("load") or {}
    total = load.get("slots_total", 0) or 1
    inflight = snap.get("inflight", 0) / total
    if stale:
        return W_INFLIGHT * inflight
    occ = load.get("slots_occupied", 0) / total
    queue = load.get("queue_depth", 0) / total
    kv_total = load.get("kv_pages_total", 0)
    kv = (1.0 - load.get("kv_pages_free", 0) / kv_total) if kv_total else 0.0
    lane = 0.0
    if slo_class:
        cls = (load.get("classes") or {}).get(slo_class)
        if cls:
            cap = cls.get("capacity", 0) or 1
            lane = (cls.get("inflight", 0) + cls.get("waiting", 0)) / cap
    return (W_OCCUPANCY * occ + W_QUEUE * queue + W_KV * kv
            + W_INFLIGHT * inflight + W_CLASS * lane)


def saturated(snap: dict) -> bool:
    """Is this replica's warm cache worth queueing for? No: a full slot
    pool WITH a backlog means affinity would trade TTFT-queue-time for
    prefill-time — strictly worse once the queue is nonempty."""
    load = snap.get("load") or {}
    total = load.get("slots_total", 0)
    return (total > 0 and load.get("slots_occupied", 0) >= total
            and load.get("queue_depth", 0) > 0)


#: replica lifecycle states (elastic fleet): only ``active`` replicas are
#: pick()-able. A scaled-up replica registers as ``joining`` (probed and
#: pre-warmed, but taking no traffic) until the supervisor activates it;
#: a retiring replica is marked ``draining`` (finishes its in-flight
#: streams, takes no new picks and is never a resume target) and becomes
#: ``gone`` when deregistered. Statically-configured replicas start
#: ``active`` — the classic fixed fleet is the degenerate lifecycle.
LIFECYCLE_JOINING = "joining"
LIFECYCLE_ACTIVE = "active"
LIFECYCLE_DRAINING = "draining"
LIFECYCLE_GONE = "gone"
LIFECYCLES = (LIFECYCLE_JOINING, LIFECYCLE_ACTIVE, LIFECYCLE_DRAINING,
              LIFECYCLE_GONE)


@guarded_by("_lock", "_ready", "_info", "_failures", "_circuit_until",
            "_inflight", "_probed_at", "_clock_offset_us", "_replica_id",
            "_state")
class Replica:
    """One upstream ``dllama-api`` process as the router sees it: the last
    probe verdict + load snapshot, the passive circuit breaker, the
    router-side in-flight count, and the elastic-fleet lifecycle state.
    All mutable state lives behind ``_lock``; readers take
    :meth:`snapshot` — no caller ever holds two replica locks, so the
    lock graph stays acyclic by construction."""

    def __init__(self, host: str, port: int, circuit_base_s: float = 0.25,
                 circuit_max_s: float = 5.0,
                 lifecycle: str = LIFECYCLE_ACTIVE):
        if lifecycle not in LIFECYCLES:
            raise ValueError(f"unknown lifecycle {lifecycle!r} "
                             f"(know {LIFECYCLES})")
        self.host = host
        self.port = port
        self.name = f"{host}:{port}"
        self.circuit_base_s = circuit_base_s
        self.circuit_max_s = circuit_max_s
        self._lock = threading.Lock()
        # optimistic until the first probe: a just-configured replica takes
        # traffic immediately, and a dead one trips the passive breaker on
        # its first connect error anyway
        self._ready = True
        self._info: dict = {}
        self._failures = 0
        self._circuit_until = 0.0
        self._inflight = 0
        self._probed_at = 0.0
        # monotonic-clock skew estimate vs this replica (trace stitching)
        # and the replica's self-reported identity (restart detection)
        self._clock_offset_us = 0
        self._replica_id = None
        self._state = lifecycle

    def set_lifecycle(self, state: str) -> None:
        if state not in LIFECYCLES:
            raise ValueError(f"unknown lifecycle {state!r} "
                             f"(know {LIFECYCLES})")
        with self._lock:
            self._state = state

    def lifecycle(self) -> str:
        with self._lock:
            return self._state

    def mark_probe(self, ready: bool, info: dict | None,
                   offset_us: int | None = None):
        """Record one active-probe verdict. A ready probe also closes the
        passive circuit: the replica answered /ready, so connect errors
        that opened the breaker are behind us.

        Returns the PREVIOUS replica identity when this probe observed a
        generation change (a different process now answers on host:port —
        a crash-restart the caller should log), else None."""
        prev_gen = None
        with self._lock:
            self._ready = ready
            self._probed_at = time.monotonic()
            if info is not None:
                self._info = info
                rid = info.get("replica_id")
                if rid is not None:
                    if self._replica_id is not None and rid != self._replica_id:
                        prev_gen = self._replica_id
                    self._replica_id = rid
            if offset_us is not None:
                self._clock_offset_us = int(offset_us)
            if ready:
                self._failures = 0
                self._circuit_until = 0.0
        return prev_gen

    def probe_age_s(self) -> float:
        """Seconds since the last completed probe (nan = never probed):
        the value behind ``dllama_router_probe_age_seconds`` and the
        staleness test that demotes this replica's load snapshot."""
        with self._lock:
            if not self._probed_at:
                return float("nan")
            return time.monotonic() - self._probed_at

    def clock_offset_us(self) -> int:
        """Estimated ``replica_monotonic_us - router_monotonic_us`` from
        the last probe round trip (skew + RTT/2). Subtracting it from a
        replica's trace timestamps moves them onto the router's timeline —
        exactly what ``merge_trace_parts`` does at fleet shutdown."""
        with self._lock:
            return self._clock_offset_us

    def mark_conn_failure(self) -> None:
        """Passive circuit breaker: a data-path connect failure opens the
        circuit with exponential backoff so one dead replica costs each
        request at most one connect attempt per backoff window."""
        with self._lock:
            self._failures += 1
            backoff = min(self.circuit_max_s,
                          self.circuit_base_s * (2 ** (self._failures - 1)))
            self._circuit_until = time.monotonic() + backoff

    def mark_conn_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._circuit_until = 0.0

    def mark_unready(self) -> None:
        """Passive drain detection: the data path got a 503, so stop
        routing here now instead of waiting out the probe interval."""
        with self._lock:
            self._ready = False

    def begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def end(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "state": self._state,
                # disaggregation role the replica declared on /ready:
                # "prefill" replicas take new prompts and hand their KV to
                # a "decode" replica at first token; "both" (the default,
                # and every pre-role replica) serves end-to-end
                "role": self._info.get("role") or "both",
                "ready": self._ready,
                "circuit_open": time.monotonic() < self._circuit_until,
                "consecutive_failures": self._failures,
                "inflight": self._inflight,
                "probed_age_s": (round(time.monotonic() - self._probed_at, 3)
                                 if self._probed_at else None),
                "replica_id": self._replica_id,
                "clock_offset_us": self._clock_offset_us,
                "load": dict(self._info),
            }


@guarded_by("_lock", "_map")
class AffinityIndex:
    """Bounded LRU map from cumulative prefix hash -> replica name. One
    entry per block of every routed conversation, evicted least-recently
    -used; capacity bounds router memory regardless of traffic shape."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._map: OrderedDict = OrderedDict()

    def lookup(self, hashes: list):
        """The replica that served the LONGEST matching block prefix (the
        most warm pages), or None. Touches the hit for LRU recency."""
        with self._lock:
            for h in reversed(hashes):
                name = self._map.get(h)
                if name is not None:
                    self._map.move_to_end(h)
                    return name
        return None

    def record(self, hashes: list, name: str) -> None:
        """Point every block prefix of a successfully routed conversation
        at the replica that now holds its pages (last writer wins: after a
        failover the NEW replica is the warm one)."""
        with self._lock:
            for h in hashes:
                self._map[h] = name
                self._map.move_to_end(h)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


@guarded_by("_lock", "_map")
class CheckpointStore:
    """Bounded LRU of the latest mid-stream checkpoint per request id.

    One live stream keeps at most ONE entry (each ``dllama-ckpt`` frame
    replaces the last — only the newest snapshot can splice without
    re-generating already-forwarded tokens for nothing), and the relay
    pops the entry the moment its stream ends, so steady-state occupancy
    is the number of in-flight checkpointing streams. Capacity eviction
    drops the least-recently-touched stream, which degrades THAT stream's
    failover to the fallback matrix's ``no_ckpt`` row — a bounded store
    costs coverage under pressure, never correctness or memory.

    An entry orphaned by ABNORMAL teardown (the relay thread died before
    its ``finally`` pop — a killed router worker, an OS-level socket
    reset during the pop path) has no stream left to resume; with
    ``ttl_s`` > 0 the periodic :meth:`sweep` (the probe loop drives it)
    reclaims such entries instead of letting them squat until LRU
    pressure evicts a LIVE stream's checkpoint to make room."""

    def __init__(self, capacity: int = 256, ttl_s: float = 0.0):
        self.capacity = max(1, int(capacity))
        self.ttl_s = max(0.0, float(ttl_s))
        self._lock = threading.Lock()
        self._map: OrderedDict = OrderedDict()

    def put(self, rid: str, payload: bytes, offset: int,
            replica: str) -> None:
        """Store/replace ``rid``'s checkpoint: the kv_transfer payload and
        the client-visible byte offset its rendering state describes."""
        with self._lock:
            self._map[rid] = {"payload": payload, "offset": int(offset),
                              "replica": replica,
                              "stored_at": time.monotonic()}
            self._map.move_to_end(rid)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def get(self, rid: str):
        """The latest entry for ``rid`` (LRU-touched), or None."""
        with self._lock:
            e = self._map.get(rid)
            if e is not None:
                self._map.move_to_end(rid)
            return e

    def pop(self, rid: str) -> None:
        with self._lock:
            self._map.pop(rid, None)

    def sweep(self, now: float = None) -> int:
        """Drop every entry older than ``ttl_s`` (0 disables); returns the
        count reclaimed. A LIVE stream's entry is refreshed by every
        checkpoint frame (put() restamps ``stored_at``), so only streams
        that stopped checkpointing TTL out — and a stream that went that
        long without a frame has nothing fresher to resume from anyway."""
        if self.ttl_s <= 0:
            return 0
        if now is None:
            now = time.monotonic()
        cutoff = now - self.ttl_s
        with self._lock:
            dead = [rid for rid, e in self._map.items()
                    if e["stored_at"] < cutoff]
            for rid in dead:
                del self._map[rid]
        return len(dead)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


@guarded_by("_lock", "_map")
class HotPrompts:
    """Bounded LRU of recently-routed prompt bodies, keyed by first-block
    affinity hash: the scale-up pre-warm source. A freshly spawned
    replica replays the hottest of these through a warm sibling's
    ``/v1/prefill`` -> its own ``/v1/kv/import`` before taking traffic,
    so its radix cache holds the fleet's hot prefixes from minute zero.
    Oversized bodies are skipped (pre-warm is for hot SHORT prefixes;
    shipping a near-window prompt would serialize the join on one
    transfer) and capacity eviction drops the least-recently-seen
    conversation — a best-effort warmth hint, never request state."""

    def __init__(self, capacity: int = 32, max_bytes: int = 16384):
        self.capacity = max(1, int(capacity))
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._map: OrderedDict = OrderedDict()  # key -> (hits, body_json)

    def record(self, hashes: list, req: dict) -> None:
        try:
            body = json.dumps(req, sort_keys=True)
        except (TypeError, ValueError):
            return
        if len(body) > self.max_bytes:
            return
        key = (hashes[0] if hashes
               else hashlib.sha256(body.encode()).hexdigest())
        with self._lock:
            hits, _ = self._map.get(key, (0, None))
            self._map[key] = (hits + 1, body)
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def top(self, n: int) -> list:
        """The ``n`` hottest recorded request bodies (dicts), most-hit
        first, recency breaking ties (the LRU order is recency)."""
        with self._lock:
            items = list(self._map.values())
        items.reverse()  # LRU order is oldest-first; stable sort then
        items.sort(key=lambda hv: hv[0], reverse=True)  # keeps recent first
        return [json.loads(body) for _, body in items[:n]]

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


def merge_expositions(parts: list) -> str:
    """Merge per-replica Prometheus text expositions into one fleet view.

    ``parts`` is ``[(replica_name, exposition_text), ...]``. Every sample
    line gains a ``replica`` label, which IS the merge semantics the text
    format can express: the per-replica series stay disjoint, so counters
    sum, gauges stay attributable, and histogram buckets merge under any
    downstream ``sum by (le)`` — while each family's HELP/TYPE pair
    dedupes to one occurrence (first replica wins) so the output is still
    a valid exposition with every family's samples contiguous."""
    helps: dict = {}
    types: dict = {}
    samples: OrderedDict = OrderedDict()  # family -> relabeled sample lines
    for replica, text in parts:
        lab = 'replica="%s"' % str(replica).replace("\\", "\\\\").replace(
            '"', '\\"')
        family = None
        for line in text.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                fields = line.split(" ", 3)
                if len(fields) < 3:
                    continue
                family = fields[2]
                target = helps if fields[1] == "HELP" else types
                target.setdefault(family, line)
                samples.setdefault(family, [])
            elif not line or line.startswith("#"):
                continue
            else:
                # sample line: name[{labels}] value — _bucket/_sum/_count
                # suffixes group under the family that declared them
                name = line.split("{", 1)[0].split(" ", 1)[0]
                key = (family if family is not None
                       and name.startswith(family) else name)
                if "{" in line:
                    head, rest = line.split("{", 1)
                    relabeled = f"{head}{{{lab},{rest}"
                else:
                    head, _, value = line.partition(" ")
                    relabeled = f"{head}{{{lab}}} {value}"
                samples.setdefault(key, []).append(relabeled)
    out = []
    for family, lines in samples.items():
        if family in helps:
            out.append(helps[family])
        if family in types:
            out.append(types[family])
        out.extend(lines)
    return "\n".join(out) + "\n" if out else ""


@guarded_by("_replicas_lock", "_replicas")
class RouterState:
    """Config + fleet picture + metrics for one router process. The
    replica set is a dynamic registry (the elastic fleet registers and
    deregisters replicas live): :attr:`replicas` snapshots it as a tuple
    under ``_replicas_lock``, so readers still iterate a stable sequence
    while :meth:`register_replica`/:meth:`deregister_replica` edit the
    underlying list. Each Replica's mutable state (including its
    joining/active/draining/gone lifecycle) lives behind its own lock."""

    def __init__(self, replicas: list, retry_budget: int = 2,
                 probe_interval_s: float = 1.0,
                 connect_timeout_s: float = 2.0,
                 upstream_timeout_s: float = 0.0,
                 affinity_block: int = 256,
                 affinity_capacity: int = 4096,
                 kv_wire: str = "f32",
                 ckpt_interval: int = 32,
                 ckpt_capacity: int = 256,
                 ckpt_ttl_s: float = 600.0,
                 metrics=None, enable_flight: bool = True,
                 ts_interval: float = 1.0,
                 max_conns: int = 0,
                 header_timeout_s: float = 10.0,
                 first_byte_timeout_s: float = 0.0,
                 stall_timeout_s: float = 0.0,
                 client_stall_timeout_s: float = 30.0,
                 probe_read_timeout_s: float = 2.0):
        self._replicas_lock = threading.Lock()
        self._replicas = list(replicas)
        self.retry_budget = retry_budget
        self.probe_interval_s = probe_interval_s
        self.connect_timeout_s = connect_timeout_s
        self.upstream_timeout_s = upstream_timeout_s
        # event-loop data-plane budgets, one per edge (0 = that edge is
        # unbounded). header: the slow-loris kill — a client must land a
        # full request head within this. first_byte: connect-to-status-line
        # on the upstream hop (falls back to upstream_timeout). stall: the
        # inter-byte budget on SSE relay — an upstream silent past it is
        # treated as DEAD and checkpoint-resumed on a sibling
        # (outcome=stall). client_stall: the slow-client hard kill — a
        # client not draining its socket past this loses the connection.
        self.max_conns = max(0, int(max_conns))
        self.header_timeout_s = header_timeout_s
        self.first_byte_timeout_s = first_byte_timeout_s
        self.stall_timeout_s = stall_timeout_s
        self.client_stall_timeout_s = client_stall_timeout_s
        # probes get their own short READ deadline, distinct from connect:
        # a gray replica (accepts, then silence) costs one read timeout,
        # never a wedged probe pass
        self.probe_read_timeout_s = probe_read_timeout_s
        self.affinity_block = affinity_block
        if kv_wire not in kv_transfer.WIRE_MODES:
            raise ValueError(f"unknown --kv-wire {kv_wire!r} "
                             f"(know {kv_transfer.WIRE_MODES})")
        # wire mode the prefill replica is asked to encode migrating rows
        # in: "f32" is bit-exact, "q80" ~3.76x smaller but error-bounded,
        # "q80+f32" q80 for full pages with a bit-exact f32 tail page
        self.kv_wire = kv_wire
        # mid-stream failover: ask every streamed request's replica for a
        # checkpoint each ckpt_interval emitted tokens (0 disables both
        # the checkpoint frames and the resume orchestration)
        self.ckpt_interval = max(0, int(ckpt_interval))
        self.ckpt_store = CheckpointStore(ckpt_capacity, ttl_s=ckpt_ttl_s)
        self.affinity = AffinityIndex(affinity_capacity)
        # pre-warm source material for scaled-up replicas (see HotPrompts)
        self.hot_prompts = HotPrompts()
        self.started_at = time.time()
        # a fresh registry per router (not the process default): in-process
        # tests run several routers side by side, and the router's series
        # must never mix with an in-process replica's engine series
        self.metrics = (metrics if metrics is not None
                        else observability.MetricsRegistry())
        reg = self.metrics
        self._m_http = reg.counter(
            "dllama_router_http_requests_total",
            "Router HTTP responses written, by route and status code",
            ("route", "code"))
        self._m_picks = reg.counter(
            "dllama_router_picks_total",
            "Replica-selection decisions, by policy that made the call",
            ("reason",))
        self._m_retries = reg.counter(
            "dllama_router_retries_total",
            "Requests re-dispatched to another replica after a retriable "
            "upstream failure (connect error or 503)")
        self._m_upstream_errors = reg.counter(
            "dllama_router_upstream_errors_total",
            "Upstream hops that failed before a usable response",
            ("replica",))
        self._m_probe_failures = reg.counter(
            "dllama_router_probe_failures_total",
            "Active /ready probes that errored (connect/parse/injected)",
            ("replica",))
        self._m_probe_errors = reg.counter(
            "dllama_router_probe_errors_total",
            "Active /ready probes that errored, by failure mode: connect "
            "(refused/unreachable), stall (the GRAY failure — the replica "
            "accepted the socket then went silent past the probe read "
            "deadline; marked circuit-open immediately), parse (garbled "
            "body), injected",
            ("replica", "reason"))
        self._m_sheds = reg.counter(
            "dllama_router_sheds_total",
            "Connections refused at accept time, before any per-connection "
            "state was allocated (max_conns = the --max-conns admission "
            "gate answered 503 + Retry-After; injected = the conn_accept "
            "fault seam fired)",
            ("reason",))
        self._m_conns = reg.gauge(
            "dllama_router_open_conns",
            "Client connections currently open on the event loop (the "
            "number --max-conns admission-controls)")
        self._m_client_disconnects = reg.counter(
            "dllama_router_client_disconnects_total",
            "Streaming clients that vanished mid-SSE (the upstream replica "
            "connection is closed immediately so its cancel-on-disconnect "
            "fires within one chunk)")
        self._m_replicas_ready = reg.gauge(
            "dllama_router_replicas_ready",
            "Replicas currently in rotation (ready and circuit closed)")
        self._m_ttfb = reg.histogram(
            "dllama_router_upstream_ttfb_ms",
            "Upstream time-to-first-byte (connect + status line) per hop")
        self._m_hop = reg.histogram(
            "dllama_router_hop_ms",
            "Per-hop latency attribution: the router's wall time split into "
            "connect (to upstream first byte), the replica's own "
            "Server-Timing queue/compute phases, and the relay stream",
            ("phase",))
        self._m_federate_errors = reg.counter(
            "dllama_router_federate_errors_total",
            "Per-replica /metrics scrapes behind /metrics/fleet that failed "
            "(connect/parse/injected); the replica drops out of that merged "
            "exposition, never the endpoint",
            ("replica",))
        self._m_federate_skipped = reg.counter(
            "dllama_router_federate_skipped_total",
            "Replicas left out of a /metrics/fleet, /metrics/history or "
            "/alerts federation pass, by reason (not_ready/circuit_open: "
            "the probe verdict excluded them; unreachable: the scrape "
            "itself failed) — a hole in the federated picture is counted, "
            "never silent",
            ("reason",))
        self._m_migrations = reg.counter(
            "dllama_kv_transfer_migrations_total",
            "Disaggregated prefill->decode migration attempts the router "
            "orchestrated, by outcome (ok = handoff relayed end-to-end; "
            "prefill_done = the row finished during prefill so nothing "
            "migrated; every *_fallback/injected/no_* outcome degraded to "
            "normal routing, i.e. a full re-prefill, never a client error)",
            ("outcome",))
        self._m_resumes = reg.counter(
            "dllama_stream_resume_total",
            "Mid-stream failover resume attempts after an upstream died "
            "mid-SSE, by outcome (ok = the stream continued bit-identically "
            "on a sibling replica after a clean death; stall = same, but "
            "the death verdict came from the inter-byte --stall-timeout "
            "budget on a silent upstream; every other outcome — no_ckpt, "
            "stale_ckpt, admit_failed, no_replica, injected, exhausted — "
            "ended the stream with a clean SSE error event + [DONE], never "
            "a silent TCP cut)",
            ("outcome",))
        self._m_ckpt_entries = reg.gauge(
            "dllama_router_ckpt_entries",
            "Live checkpoints in the router's bounded resume store (one "
            "per in-flight checkpointing stream; popped at stream end)")
        self._m_ckpt_entries.set_function(self.ckpt_store.__len__)
        self._m_ckpt_expired = reg.counter(
            "dllama_router_ckpt_expired_total",
            "Checkpoint-store entries reclaimed by the TTL sweep (orphaned "
            "by abnormal stream teardown — no relay was left to pop them); "
            "LRU capacity eviction is NOT counted here")
        self._m_fleet_replicas = reg.gauge(
            "dllama_fleet_replicas",
            "Replicas currently registered with the router (every "
            "lifecycle state but gone: joining and draining replicas are "
            "paid-for capacity even while they take no new picks)")
        self._m_fleet_replicas.set_function(self._count_registered)
        self._m_scale_events = reg.counter(
            "dllama_fleet_scale_events_total",
            "Elastic-fleet scale transitions, by event (joined/draining/"
            "retired are the normal lifecycle edges; spawn_failed/"
            "prewarm_fallback/drain_killed/injected count the degraded "
            "paths — every failure mode is a row here, never a silent "
            "retry loop)",
            ("event",))
        self._m_policy_evals = reg.counter(
            "dllama_fleet_policy_evals_total",
            "Autoscaler policy-engine evaluations, by decision (up/down/"
            "hold, or injected when the policy_eval fault seam fired and "
            "the tick was skipped)",
            ("decision",))
        self._m_probe_age = reg.gauge(
            "dllama_router_probe_age_seconds",
            "Seconds since each replica's last completed /ready probe "
            "(absent until one completes); pick() stops trusting a load "
            "snapshot older than twice the probe interval",
            ("replica",))
        for r in self._replicas:
            self._m_probe_age.set_function(r.probe_age_s, replica=r.name)
        # the router's own flight recorder — like its registry, never the
        # process default: in-process fleet tests run replicas beside it
        # and the rings must not mix
        self.flight = (observability.FlightRecorder(process="router")
                       if enable_flight else None)
        # the router's own bounded metric history (GET /metrics/history
        # answers it under "router", next to the federated replica views);
        # the sampler thread starts/stops with the probe loop
        self.ts_store = TimeSeriesStore()
        self.sampler = Sampler(reg, self.ts_store, interval_s=ts_interval)
        self._probe_supervisor = None
        self._probe_stop = threading.Event()

    # -- the dynamic replica registry -------------------------------------

    @property
    def replicas(self) -> tuple:
        """A point-in-time snapshot of the registered replica set. Every
        reader iterates this tuple (never the underlying list), so a
        concurrent register/deregister changes what the NEXT reader sees,
        never what the current one is iterating."""
        with self._replicas_lock:
            return tuple(self._replicas)

    def _count_registered(self) -> int:
        with self._replicas_lock:
            return len(self._replicas)

    def register_replica(self, host: str, port: int,
                         lifecycle: str = LIFECYCLE_JOINING):
        """Add a replica to the routing set (idempotent by host:port —
        re-registering an existing name returns the existing Replica).
        New elastic replicas join as ``joining``: probed, federated into
        the fleet picture, but invisible to pick() until
        :meth:`activate_replica`."""
        name = f"{host}:{port}"
        with self._replicas_lock:
            for r in self._replicas:
                if r.name == name:
                    return r
            r = Replica(host, port, lifecycle=lifecycle)
            self._replicas = self._replicas + [r]
        self._m_probe_age.set_function(r.probe_age_s, replica=r.name)
        if self.flight is not None:
            self.flight.record("replica_register", replica=name,
                               lifecycle=lifecycle)
        return r

    def activate_replica(self, name: str) -> bool:
        """joining -> active: the replica starts taking picks. Counted as
        the ``joined`` scale event (the marker `cli top` renders)."""
        for r in self.replicas:
            if r.name == name:
                r.set_lifecycle(LIFECYCLE_ACTIVE)
                self._m_scale_events.inc(event="joined")
                return True
        return False

    def drain_replica(self, name: str) -> bool:
        """active -> draining: no new picks, no resume targeting, but the
        replica keeps its in-flight streams (and stays federated) until
        the supervisor finishes the drain."""
        for r in self.replicas:
            if r.name == name:
                r.set_lifecycle(LIFECYCLE_DRAINING)
                self._m_scale_events.inc(event="draining")
                return True
        return False

    def deregister_replica(self, name: str) -> bool:
        """Remove a replica from the routing set (the ``retired`` scale
        event). Its probe-age gauge series is retired with it — the
        callback is swapped for NaN, which the gauge renderer skips."""
        gone = None
        with self._replicas_lock:
            for r in self._replicas:
                if r.name == name:
                    gone = r
                    break
            if gone is None:
                return False
            self._replicas = [r for r in self._replicas if r.name != name]
        gone.set_lifecycle(LIFECYCLE_GONE)
        self._m_probe_age.set_function(lambda: float("nan"), replica=name)
        self._m_scale_events.inc(event="retired")
        if self.flight is not None:
            self.flight.record("replica_deregister", replica=name)
        return True

    # -- routing ----------------------------------------------------------

    def pick(self, hashes: list, exclude=frozenset(), role: str = None,
             slo_class: str = None):
        """Choose the replica for one dispatch attempt: (replica, reason).

        Fires the ``route_pick`` seam (an injected fault here surfaces as
        a 5xx the ingress counter sees). Affinity wins when its target is
        routable and unsaturated; otherwise weighted least-load over every
        routable replica not already tried this request, with the
        request's SLO-class lane pressure folded into the score
        (``slo_class`` — see :func:`load_score`).

        ``role`` narrows the candidate set to replicas that DECLARED that
        disaggregation role (the migration hops). Normal picks
        (``role=None``) exclude dedicated-prefill replicas — their slots
        exist to turn prompts around fast, not to hold whole decodes —
        unless they are the only routable capacity left (availability
        beats placement policy)."""
        faults.fire("route_pick")
        candidates = []
        spares = []  # dedicated-prefill replicas, normal traffic's last resort
        for r in self.replicas:
            if r.name in exclude:
                continue
            s = r.snapshot()
            if s["state"] != LIFECYCLE_ACTIVE:
                continue  # joining replicas are still pre-warming;
                #            draining ones must never gain NEW streams
                #            (that includes resume targeting — a resumed
                #            stream would just need a second failover)
            if not (s["ready"] and not s["circuit_open"]):
                continue
            if role is not None:
                if s["role"] == role:
                    candidates.append((r, s))
            elif s["role"] == "prefill":
                spares.append((r, s))
            else:
                candidates.append((r, s))
        if role is None and not candidates:
            candidates = spares
        if not candidates:
            raise NoReplicaAvailable(len(self.replicas), len(exclude),
                                     retry_after_s=max(
                                         1.0, self.probe_interval_s))
        reason = "least_load"
        if hashes:
            target = self.affinity.lookup(hashes)
            if target is not None:
                for r, s in candidates:
                    if r.name != target:
                        continue
                    if not saturated(s):
                        self._m_picks.inc(reason="affinity")
                        return r, "affinity"
                    reason = "affinity_fallback"
                    break
        # probe-staleness fallback: a snapshot older than 2x the probe
        # interval no longer describes the replica (wedged probe loop,
        # slow-walking /ready) — weight those candidates by the router's
        # own live in-flight count only
        stale_after_s = 2.0 * self.probe_interval_s
        r, _ = min(candidates,
                   key=lambda rs: load_score(
                       rs[1],
                       stale=(rs[1]["probed_age_s"] is not None
                              and rs[1]["probed_age_s"] > stale_after_s),
                       slo_class=slo_class))
        self._m_picks.inc(reason=reason)
        return r, reason

    def disagg_ready(self) -> bool:
        """Is the migration path open RIGHT NOW? Requires at least one
        routable dedicated-prefill AND one routable dedicated-decode
        replica. "both" replicas don't count toward either side — they
        serve end-to-end, and a fleet of only those never migrates."""
        roles = set()
        for r in self.replicas:
            s = r.snapshot()
            if (s["state"] == LIFECYCLE_ACTIVE
                    and s["ready"] and not s["circuit_open"]):
                roles.add(s["role"])
        return "prefill" in roles and "decode" in roles

    # -- probing ----------------------------------------------------------

    def probe_replica(self, r: Replica) -> bool:
        """One active /ready probe. Fires the ``probe`` seam; any failure
        (connect, stall, unparseable body, injected) is a DOWN verdict
        that takes the replica out of rotation until a probe succeeds.

        Two deadlines, one per edge: ``connect_timeout_s`` covers the TCP
        connect, then the socket is re-armed with the (short)
        ``probe_read_timeout_s`` for the response read. A GRAY replica —
        one that accepts the socket and then never answers — used to cost
        the whole connect timeout per probe pass AND read as merely
        not-ready; now it costs one read deadline, is counted under
        ``dllama_router_probe_errors_total{reason=stall}``, and is marked
        circuit-open immediately (accepting-but-silent is worse than
        refusing: the data path would hang there too)."""
        connected = False
        try:
            faults.fire("probe")
            conn = http.client.HTTPConnection(
                r.host, r.port, timeout=self.connect_timeout_s)
            try:
                conn.connect()
                connected = True
                if conn.sock is not None:
                    conn.sock.settimeout(
                        self.probe_read_timeout_s or self.connect_timeout_s)
                t_send = time.monotonic()
                conn.request("GET", "/ready",
                             headers={HDR_REQUEST_ID:
                                      observability.new_request_id()})
                resp = conn.getresponse()
                body = resp.read()
                t_recv = time.monotonic()
            finally:
                conn.close()
            info = json.loads(body) if body else {}
            if not isinstance(info, dict):
                raise ValueError("non-object /ready body")
            ready = resp.status == 200
            # clock-offset estimate for trace stitching: the replica stamps
            # /ready with its monotonic-epoch time_us; assuming the reply
            # was stamped mid-round-trip, the difference to our own
            # mid-point is skew (error bounded by RTT/2 — microseconds on
            # loopback, where fleets under one router live)
            offset_us = None
            t_us = info.get("time_us")
            if isinstance(t_us, (int, float)):
                mid_us = observability.mono_to_us((t_send + t_recv) / 2.0)
                offset_us = int(t_us) - mid_us
            prev_gen = r.mark_probe(ready, info, offset_us=offset_us)
            if prev_gen is not None:
                new_gen = info.get("replica_id")
                print(f"🔁 router: replica {r.name} restarted "
                      f"(generation {prev_gen} -> {new_gen})",
                      file=sys.stderr)
                if self.flight is not None:
                    self.flight.record("replica_generation",
                                       replica=r.name, prev=prev_gen,
                                       new=new_gen)
            return ready
        except (OSError, ValueError, faults.FaultInjected) as e:
            # an unreachable/garbled probe IS the health signal, not an
            # error to propagate: record DOWN and keep the loop alive
            r.mark_probe(False, None)
            if isinstance(e, faults.FaultInjected):
                reason = "injected"
            elif isinstance(e, TimeoutError) and connected:
                reason = "stall"
                r.mark_conn_failure()  # gray: circuit-open NOW, not just
                #                        not-ready — the data path would
                #                        hang on this replica too
            elif isinstance(e, ValueError):
                reason = "parse"
            else:
                reason = "connect"
            self._m_probe_failures.inc(replica=r.name)
            self._m_probe_errors.inc(replica=r.name, reason=reason)
            return False

    def probe_once(self) -> int:
        """Probe the whole fleet; returns (and gauges) the in-rotation
        count."""
        n_ready = 0
        for r in self.replicas:
            if self.probe_replica(r):
                n_ready += 1
        self._m_replicas_ready.set(float(n_ready))
        # the probe cadence doubles as the checkpoint-store TTL sweep:
        # entries orphaned by abnormal stream teardown are reclaimed here
        # instead of squatting until LRU pressure evicts a live stream's
        expired = self.ckpt_store.sweep()
        if expired:
            self._m_ckpt_expired.inc(expired)
        return n_ready

    def _probe_loop(self) -> None:
        while not self._probe_stop.is_set():
            self.probe_once()
            self._probe_stop.wait(self.probe_interval_s)

    def start_probes(self) -> None:
        """Start the background probe loop (idempotent), supervised the
        same way the replica scheduler is: a crashed loop restarts rather
        than silently freezing the fleet picture at its last snapshot."""
        if self._probe_supervisor is not None:
            return
        self._probe_supervisor = Supervisor(
            self._probe_loop,
            on_crash=lambda exc: None,  # state is probe-local; next round
            name="dllama-router-probe")  # rebuilds it from scratch
        self._probe_supervisor.start()
        self.sampler.start()  # history rides the probe loop's lifetime

    def stop_probes(self) -> None:
        self._probe_stop.set()
        self.sampler.stop()
        if self._probe_supervisor is not None:
            self._probe_supervisor.stop()

    # -- views ------------------------------------------------------------

    def readiness(self) -> tuple:
        """(ready, info) for the router's own /ready: ready iff at least
        one replica is in rotation. The info aggregates the fleet load
        picture so one curl answers 'can you take traffic, and how much'."""
        snaps = [r.snapshot() for r in self.replicas]
        routable = [s for s in snaps
                    if s["state"] == LIFECYCLE_ACTIVE
                    and s["ready"] and not s["circuit_open"]]
        agg = {
            "slots_occupied": 0, "slots_total": 0, "queue_depth": 0,
            "kv_pages_free": 0, "kv_pages_total": 0,
            "kv_pages_reclaimable": 0,
        }
        for s in routable:
            load = s.get("load") or {}
            for k in agg:
                agg[k] += load.get(k, 0)
            # radix-cached pages are evictable on demand: capacity the
            # autoscaler must see as available, or a warmed-up idle
            # fleet reads as saturated forever and never scales down
            agg["kv_pages_reclaimable"] += (
                (load.get("kv_pages") or {}).get("pages_cached", 0))
        return len(routable) > 0, {
            "status": "ready" if routable else "not_ready",
            "replicas_total": len(snaps),
            "replicas_ready": len(routable),
            "fleet": agg,
            "replicas": snaps,
        }

    def stats(self) -> dict:
        ready, info = self.readiness()
        return {
            "role": "router",
            "uptime_s": round(time.time() - self.started_at, 1),
            "ready": ready,
            "affinity_entries": len(self.affinity),
            "load": info,
            "metrics": self.metrics.snapshot(),
        }

    # -- fleet observability ----------------------------------------------

    def _scrape(self, r: Replica, path: str) -> bytes:
        """One GET against a replica's local surface (metrics/flight)."""
        conn = http.client.HTTPConnection(
            r.host, r.port, timeout=self.connect_timeout_s)
        try:
            conn.request("GET", path, headers={
                HDR_REQUEST_ID: observability.new_request_id()})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise ValueError(f"{path} -> {resp.status}")
            return body
        finally:
            conn.close()

    def federate(self) -> str:
        """The /metrics/fleet body: every in-rotation replica's /metrics,
        merged under a ``replica`` label. Crashed/draining replicas fall
        out of the merge with their circuit/ready verdict, so a restarted
        replica never leaves stale series behind; a failed scrape (fires
        the ``federate_scrape`` seam) is counted and skipped — the
        endpoint itself always answers."""
        parts = []
        for r in self.replicas:
            body = self._federated_scrape(r, "/metrics")
            if body is not None:
                parts.append((r.name, body.decode("utf-8", "replace")))
        return merge_expositions(parts)

    def _federated_scrape(self, r: Replica, path: str):
        """One replica's contribution to a federation pass, or None —
        every skip is counted by reason in
        ``dllama_router_federate_skipped_total`` (a hole in the federated
        picture must be machine-visible, not a silent absence)."""
        s = r.snapshot()
        if not s["ready"]:
            self._m_federate_skipped.inc(reason="not_ready")
            return None
        if s["circuit_open"]:
            self._m_federate_skipped.inc(reason="circuit_open")
            return None
        try:
            faults.fire("federate_scrape")
            return self._scrape(r, path)
        except (OSError, ValueError, faults.FaultInjected):
            self._m_federate_errors.inc(replica=r.name)
            self._m_federate_skipped.inc(reason="unreachable")
            return None

    def federate_history(self, window_s: float) -> dict:
        """The /metrics/history federation: the router's own window plus
        every in-rotation replica's, keyed per replica."""
        out = {"window_s": window_s,
               "router": self.ts_store.window(window_s), "replicas": {}}
        for r in self.replicas:
            body = self._federated_scrape(
                r, f"/metrics/history?window={window_s:g}")
            if body is None:
                continue
            try:
                out["replicas"][r.name] = json.loads(body)
            except ValueError:
                self._m_federate_errors.inc(replica=r.name)
        return out

    def federate_alerts(self) -> dict:
        """The /alerts federation: every in-rotation replica's burn-rate
        alert picture, with a fleet-wide firing count on top."""
        out = {"replicas": {}, "firing": 0}
        for r in self.replicas:
            body = self._federated_scrape(r, "/alerts")
            if body is None:
                continue
            try:
                payload = json.loads(body)
            except ValueError:
                self._m_federate_errors.inc(replica=r.name)
                continue
            out["replicas"][r.name] = payload
            out["firing"] += int(payload.get("firing") or 0)
        return out

    def flight_report(self) -> dict:
        """The router's own flight ring plus every replica's /debug/flight
        — the aggregate a postmortem starts from after an upstream
        failure. Unreachable replicas (usually exactly the interesting
        ones) report their routing verdict in place of a ring; their
        on-crash dump lives in $DLLAMA_FLIGHT on disk."""
        out = {
            "router": (self.flight.snapshot()
                       if self.flight is not None else None),
            "replicas": {},
        }
        for r in self.replicas:
            s = r.snapshot()
            try:
                out["replicas"][r.name] = json.loads(
                    self._scrape(r, "/debug/flight"))
            except (OSError, ValueError):
                out["replicas"][r.name] = {
                    "error": "unreachable",
                    "ready": s["ready"],
                    "circuit_open": s["circuit_open"],
                }
        return out


class ClientGone(OSError):
    """The client vanished mid-response: EOF/reset on its socket, a write
    stalled past the client-stall budget, or an injected ``client_write``
    fault. Raised (once counted) so every relay unwinds through its
    ``finally`` blocks — the upstream socket closes within one chunk and
    the replica's cancel-on-disconnect frees the decode slot."""


class RouterConnection:
    """One client connection on the event loop — the front-door HTTP
    surface. Local routes (/health /ready /metrics /stats) answer from
    RouterState; everything else on the OpenAI surface proxies to a
    picked replica with failover. Every response — local, proxied, or
    error — echoes X-Request-Id, and the same id travels on the upstream
    hop so one grep correlates router and replica traces.

    The connection is a single coroutine (:meth:`run`) driven by the
    server's :class:`~dllama_tpu.serving.evloop.Loop`: requests are read
    under the header deadline (keep-alive between them), responses are
    written under the client-stall deadline, and the relay loops hold at
    most one chunk in hand — a slow client pauses its upstream read
    instead of growing router RSS. Every method here runs ON the loop
    thread: no blocking calls allowed (LOOP-001 enforces the shortlist);
    control-plane work that legitimately blocks (federation scrapes over
    http.client) is shipped to a worker via ``evloop.run_in_thread``."""

    _KNOWN_ROUTES = ("/v1/chat/completions", "/chat/completions",
                     "/v1/models", "/health", "/healthz", "/ready",
                     "/metrics", "/metrics/fleet", "/metrics/history",
                     "/alerts", "/stats", "/debug/flight")

    def __init__(self, server, state: RouterState, sock, addr):
        self.server = server
        self.state = state
        self.sock = sock
        self.addr = addr
        self.buf = bytearray()  # client bytes carried across requests
        self.req = None
        self.path = "/"
        self.close_after = False
        self._client_counted = False  # one disconnect count per connection

    # -- the connection loop ----------------------------------------------

    @loop_callback
    def run(self):
        st = self.state
        try:
            while True:
                deadline = (time.monotonic() + st.header_timeout_s
                            if st.header_timeout_s else None)
                try:
                    req = yield from evloop.read_request(
                        self.sock, self.buf, deadline)
                except evloop.HttpError as e:
                    self._begin_request(None)
                    self.close_after = True
                    yield from self._error(e.status, str(e))
                    return
                if req is None:
                    return  # clean keep-alive close
                self.req = req
                self.path = req.path
                self.close_after = not req.keep_alive
                self._begin_request(req)
                if req.method == "GET":
                    yield from self._do_GET()
                elif req.method == "POST":
                    yield from self._do_POST()
                else:
                    yield from self._error(
                        405, f"method {req.method} not allowed")
                if self.close_after:
                    return
        except (evloop.ProtocolError, evloop.LoopTimeout, ClientGone):
            # garbled head, slow-loris past the header budget, or a client
            # that vanished/stalled mid-response: nothing left to answer
            return

    def _begin_request(self, req) -> None:
        self._rid = observability.sanitize_request_id(
            req.header(HDR_REQUEST_ID) if req is not None else None)
        self._t_begin = time.monotonic()
        # one router span per request: its pid:span value is BOTH the
        # X-Dllama-Parent-Span the replica parents its trace under and the
        # flow-arrow id tying the two process tracks together
        self._span_id = observability.next_span_id()
        self._parent_value = observability.parent_span_value(self._span_id)

    def _route(self) -> str:
        p = self.path.split("?", 1)[0]
        return p if p in self._KNOWN_ROUTES else "other"

    def _count(self, code: int) -> None:
        self.state._m_http.inc(route=self._route(), code=str(code))

    def _server_timing(self) -> str:
        return f"total;dur={(time.monotonic() - self._t_begin) * 1e3:.3f}"

    def _client_deadline(self):
        t = self.state.client_stall_timeout_s
        return time.monotonic() + t if t else None

    # -- writing to the client --------------------------------------------

    @loop_callback
    def _send(self, data: bytes):
        """One client write under the client-stall budget. A failed or
        stalled write (or an injected ``client_write`` fault) counts the
        disconnect ONCE for the connection and raises ClientGone."""
        try:
            faults.fire("client_write")
            yield from evloop.send_all(self.sock, data,
                                       self._client_deadline())
        except (OSError, faults.FaultInjected) as e:
            if not self._client_counted:
                self._client_counted = True
                self.state._m_client_disconnects.inc()
            raise ClientGone(f"client write failed: {e}")

    @loop_callback
    def _respond(self, code: int, headers: list, body: bytes):
        """One complete framed response (the common, non-SSE shape):
        Content-Length so keep-alive survives, standard response headers,
        a single send."""
        hs = list(headers)
        hs.append(("Content-Length", str(len(body))))
        hs.append((HDR_REQUEST_ID, self._rid))
        hs.append((HDR_SERVER_TIMING, self._server_timing()))
        if self.close_after:
            hs.append(("Connection", "close"))
        self._count(code)
        yield from self._send(evloop.response_bytes(code, hs, body))

    @loop_callback
    def _json(self, code: int, obj: dict, headers: dict = None):
        hs = [("Content-Type", "application/json")]
        for k, v in (headers or {}).items():
            hs.append((k, v))
        yield from self._respond(code, hs, json.dumps(obj).encode())

    @loop_callback
    def _text(self, code: int, body: bytes):
        yield from self._respond(
            code,
            [("Content-Type", "text/plain; version=0.0.4; charset=utf-8")],
            body)

    @loop_callback
    def _error(self, code: int, message: str):
        yield from self._json(code, {"error": {"message": message,
                                               "type": "router_error",
                                               "request_id": self._rid}})

    @loop_callback
    def _lifecycle_error(self, e: LifecycleError):
        headers = {}
        if e.retry_after_s is not None:
            headers["Retry-After"] = str(max(1, int(round(e.retry_after_s))))
        yield from self._json(
            e.http_status,
            {"error": {"message": str(e), "type": "server_error",
                       "request_id": self._rid}},
            headers=headers)

    # -- upstream deadlines -----------------------------------------------

    def _deadline(self, seconds: float):
        return time.monotonic() + seconds if seconds else None

    def _first_byte_deadline(self):
        st = self.state
        return self._deadline(st.first_byte_timeout_s
                              or st.upstream_timeout_s)

    def _body_deadline(self):
        return self._deadline(self.state.upstream_timeout_s)

    def _stall_deadline(self):
        st = self.state
        return self._deadline(st.stall_timeout_s or st.upstream_timeout_s)

    # -- local routes -----------------------------------------------------

    @loop_callback
    def _do_GET(self):
        st = self.state
        bare = self.path.split("?", 1)[0]
        if bare in ("/health", "/healthz"):
            # LIVENESS of the router process itself: 200 whenever it can
            # answer, even with zero routable replicas (readiness's job)
            _, info = st.readiness()
            yield from self._json(
                200, {"status": "ok", "role": "router",
                      "replicas_total": info["replicas_total"],
                      "replicas_ready": info["replicas_ready"]})
        elif bare == "/ready":
            ready, info = st.readiness()
            yield from self._json(200 if ready else 503, info)
        elif bare == "/metrics":
            yield from self._text(200, st.metrics.render().encode())
        elif bare == "/metrics/fleet":
            # federation scrapes the fleet over blocking http.client: a
            # worker thread's job, never the loop's
            body = yield from evloop.run_in_thread(st.federate)
            yield from self._text(200, body.encode())
        elif bare == "/metrics/history":
            # federated time-series history: the router's own window plus
            # every in-rotation replica's, per-replica keyed
            window = parse_window(self.path)
            obj = yield from evloop.run_in_thread(
                lambda: st.federate_history(window))
            yield from self._json(200, obj)
        elif bare == "/alerts":
            # the fleet's live SLO burn-rate picture (replica-evaluated;
            # the router only federates)
            obj = yield from evloop.run_in_thread(st.federate_alerts)
            yield from self._json(200, obj)
        elif bare == "/stats":
            yield from self._json(200, st.stats())
        elif bare == "/debug/flight":
            obj = yield from evloop.run_in_thread(st.flight_report)
            yield from self._json(200, obj)
        elif bare == "/v1/models":
            # model identity is fleet-wide (one model per fleet): proxy to
            # any routable replica
            yield from self._proxy("GET", b"", affinity_hashes=[])
        else:
            yield from self._error(404, f"unknown path {self.path}")

    @loop_callback
    def _do_POST(self):
        bare = self.path.split("?", 1)[0]
        if bare not in ("/v1/chat/completions", "/chat/completions"):
            yield from self._error(404, f"unknown path {self.path}")
            return
        body = self.req.body or b"{}"
        try:
            req = json.loads(body or b"{}")
        except ValueError:
            req = None  # let the replica speak the 400; neither affinity
            #             nor migration applies to an unparseable body
        hashes = []
        if self.state.affinity_block > 0 and isinstance(req, dict):
            try:
                hashes = prefix_hashes(req.get("messages") or [],
                                       self.state.affinity_block)
            except (ValueError, AttributeError):
                pass  # malformed messages: no affinity hint, routing
                #       still proceeds (the replica owns the 400)
        if isinstance(req, dict) and req.get("messages"):
            # remember the conversation as pre-warm material: a scaled-up
            # replica replays the hottest of these before taking traffic
            self.state.hot_prompts.record(hashes, req)
        if isinstance(req, dict):
            migrated = yield from self._try_disagg(req, hashes)
            if migrated:
                return  # migrated (or finished at the prefill replica)
        yield from self._proxy(
            "POST", body, affinity_hashes=hashes,
            slo_class=(self.req.header(HDR_CLASS)
                       or "").strip().lower() or None)

    # -- disaggregated migration ------------------------------------------

    @loop_callback
    def _try_disagg(self, req: dict, hashes: list):
        """One migration attempt: prefill hop -> KV relay -> decode hop.

        Returns True iff the request was fully answered here — either the
        decode replica took the handoff and streamed the rest of the row,
        or the row finished during prefill and the prefill replica's
        client-shape answer was relayed verbatim. EVERY failure path
        returns False so _do_POST falls back to normal routing (a full
        re-prefill on whatever replica pick() chooses): a dead decode
        replica or torn transfer costs latency, never a client error.

        Fires the ``migrate`` seam at the decision point (an injected
        fault here exercises exactly that fallback); the hops fire the
        same ``route_pick``/``proxy_upstream`` seams as normal traffic."""
        st = self.state
        if not st.disagg_ready():
            return False
        if int(req.get("n") or 1) != 1:
            # the prefill endpoint rejects n>1 (it fans out) — route
            # normally. Stop strings migrate fine since the detector's
            # scanback travels in the v2 transfer header.
            return False
        outcome = "prefill_fallback"
        detail: dict = {}
        t0 = time.monotonic()
        try:
            try:
                faults.fire("migrate")
            except faults.FaultInjected:
                outcome = "injected"
                return False
            # -- hop 1: prefill -------------------------------------------
            try:
                prefill, _ = st.pick(hashes, role="prefill")
            except (NoReplicaAvailable, faults.FaultInjected):
                outcome = "no_prefill"
                return False
            detail["prefill"] = prefill.name
            body = json.dumps(dict(req, kv_wire=st.kv_wire)).encode()
            prefill.begin()
            up = None
            try:
                try:
                    faults.fire("proxy_upstream")
                    up = yield from evloop.open_upstream(
                        self.server.pool, prefill.host, prefill.port,
                        self._deadline(st.connect_timeout_s))
                    yield from up.request(
                        "POST", "/v1/prefill", self._upstream_headers(),
                        body, self._deadline(st.connect_timeout_s))
                    resp = yield from up.get_response(
                        self._first_byte_deadline())
                except (OSError, faults.FaultInjected) as e:
                    prefill.mark_conn_failure()
                    st._m_upstream_errors.inc(replica=prefill.name)
                    detail["error"] = repr(e)[:200]
                    return False
                if resp.status != 200:
                    if resp.status == 503:
                        prefill.mark_unready()  # draining: out of rotation
                    st._m_upstream_errors.inc(replica=prefill.name)
                    detail["status"] = resp.status
                    return False
                prefill.mark_conn_success()
                ctype = resp.getheader("Content-Type") or ""
                if kv_transfer.CONTENT_TYPE not in ctype:
                    # the row finished during prefill: the reply already
                    # IS the client-shape answer — relay it verbatim
                    outcome = "prefill_done"
                    if "text/event-stream" in ctype:
                        yield from self._relay_sse(resp, up, prefill)
                    else:
                        payload = yield from resp.read_all(
                            self._body_deadline())
                        yield from self._relay_buffered(
                            resp.status, payload, self._relay_headers(resp))
                    if hashes:
                        st.affinity.record(hashes, prefill.name)
                    return True
                # the framed KV page stream, whole
                stream = yield from resp.read_all(self._body_deadline())
            finally:
                prefill.end()
                if up is not None:
                    up.close()
            # -- hop 2: decode import -------------------------------------
            tried: set = set()
            for _ in range(1 + st.retry_budget):
                try:
                    decode, _ = st.pick(hashes, role="decode",
                                        exclude=tried)
                except (NoReplicaAvailable, faults.FaultInjected):
                    break
                tried.add(decode.name)
                detail["decode"] = decode.name
                decode.begin()
                up = None
                try:
                    try:
                        faults.fire("proxy_upstream")
                        up = yield from evloop.open_upstream(
                            self.server.pool, decode.host, decode.port,
                            self._deadline(st.connect_timeout_s))
                        headers = self._upstream_headers()
                        headers["Content-Type"] = kv_transfer.CONTENT_TYPE
                        yield from up.request(
                            "POST", "/v1/kv/import", headers, stream,
                            self._deadline(st.connect_timeout_s))
                        resp = yield from up.get_response(
                            self._first_byte_deadline())
                    except (OSError, faults.FaultInjected) as e:
                        decode.mark_conn_failure()
                        st._m_upstream_errors.inc(replica=decode.name)
                        detail["error"] = repr(e)[:200]
                        continue
                    if resp.status != 200:
                        # 503 = draining, 422 = torn stream, 5xx = import
                        # blew up: none did decode work, try the next one
                        if resp.status == 503:
                            decode.mark_unready()
                        st._m_upstream_errors.inc(replica=decode.name)
                        detail["status"] = resp.status
                        continue
                    decode.mark_conn_success()
                    outcome = "ok"
                    if "text/event-stream" in (resp.getheader("Content-Type")
                                               or ""):
                        yield from self._relay_sse(resp, up, decode)
                    else:
                        payload = yield from resp.read_all(
                            self._body_deadline())
                        yield from self._relay_buffered(
                            resp.status, payload, self._relay_headers(resp))
                    # affinity points at the PREFILL replica: the next
                    # turn's prompt prefix is warm THERE (published at
                    # admit), and warm prefill is where affinity saves
                    # compute — the wire ships every block regardless of
                    # decode-side warmth
                    if hashes:
                        st.affinity.record(hashes, prefill.name)
                    return True
                finally:
                    decode.end()
                    if up is not None:
                        up.close()
            outcome = "import_fallback"
            return False
        finally:
            st._m_migrations.inc(outcome=outcome)
            if st.flight is not None:
                st.flight.record("migrate", request_id=self._rid,
                                 outcome=outcome, **detail)
            if observability.trace_path() is not None:
                us = observability.mono_to_us
                observability.emit_trace_events([
                    {"name": "router_migrate", "ph": "X",
                     "pid": os.getpid(), "tid": self._span_id,
                     "ts": us(t0),
                     "dur": max(1, us(time.monotonic()) - us(t0)),
                     "cat": "router",
                     "args": dict(detail, request_id=self._rid,
                                  outcome=outcome)},
                ])

    # -- the proxy core ---------------------------------------------------

    def _upstream_headers(self) -> dict:
        req = self.req
        h = {HDR_REQUEST_ID: self._rid,
             HDR_PARENT_SPAN: self._parent_value,
             "Content-Type": (req.header("Content-Type")
                              if req is not None else None)
             or "application/json",
             "Accept": (req.header("Accept") if req is not None else None)
             or "*/*"}
        st = self.state
        if st.ckpt_interval > 0:
            # opt every upstream stream into mid-stream checkpointing (the
            # replica ignores this for anything that can't checkpoint);
            # the checkpoint rides the same wire mode as migrations
            h[HDR_CKPT] = str(st.ckpt_interval)
            h[HDR_CKPT_WIRE] = st.kv_wire
        # the SLO class rides every upstream hop untouched: the REPLICA
        # owns validation (unknown class -> its 400 passes straight
        # through), the router only scores by it
        cls = ((req.header(HDR_CLASS) if req is not None else None)
               or "").strip()
        if cls:
            h[HDR_CLASS] = cls
        return h

    @loop_callback
    def _proxy(self, method: str, body: bytes, affinity_hashes: list,
               slo_class: str = None):
        """Dispatch one request with failover.

        Retriable = the hop died before the client received anything — a
        connect error, an injected proxy_upstream fault, a replica killed
        mid-BUFFERED-body (nothing was forwarded yet, so re-dispatch is
        safe) — or a 503 (draining / scheduler mid-restart, no decode work
        done). 429/504 and every other status pass through untouched: a
        429 means the fleet is at capacity (retrying amplifies the
        overload — the client owns the backoff) and a 504 already burned
        the request's deadline. Nothing retries once response bytes have
        been forwarded, which for SSE means once the stream began."""
        st = self.state
        tried: set = set()
        last_503 = None  # pass the FINAL 503 through on budget exhaustion
        attempts = 0
        # the hop record _finish_proxy turns into attribution histograms,
        # router trace spans and (on failure) the error verdict — filled
        # in as the dispatch progresses, reflecting the LAST attempt
        hop = {"replica": None, "status": None, "error": None,
               "t_conn": None, "t_ttfb": None, "timing": {}}
        try:
            while True:
                try:
                    replica, _reason = st.pick(affinity_hashes,
                                               exclude=tried,
                                               slo_class=slo_class)
                except NoReplicaAvailable as e:
                    if last_503 is not None:
                        hop["status"] = last_503[0]
                        yield from self._relay_buffered(*last_503)
                        return
                    hop["error"] = "no_replica"
                    hop["status"] = e.http_status
                    yield from self._lifecycle_error(e)
                    return
                except faults.FaultInjected as e:
                    # an injected route_pick fault is a router bug
                    # stand-in: surfaces as a 500 the ingress counter sees
                    hop["error"] = "route_pick"
                    hop["status"] = 500
                    yield from self._error(500, str(e))
                    return
                tried.add(replica.name)
                replica.begin()
                up = None
                handed_off = False  # up's socket pooled or owned by a relay
                t0 = time.monotonic()
                hop["replica"] = replica.name
                hop["t_conn"], hop["t_ttfb"] = t0, None
                try:
                    try:
                        faults.fire("proxy_upstream")
                        up = yield from evloop.open_upstream(
                            self.server.pool, replica.host, replica.port,
                            self._deadline(st.connect_timeout_s))
                        yield from up.request(
                            method, self.path, self._upstream_headers(),
                            body, self._deadline(st.connect_timeout_s))
                        # two-phase deadline: strict on connect/send, then
                        # the first-byte budget for the status line, then
                        # unlimited (or --upstream-timeout) for the body —
                        # a long decode must not trip the connect timeout
                        resp = yield from up.get_response(
                            self._first_byte_deadline())
                        st._m_ttfb.observe((time.monotonic() - t0) * 1000.0)
                        hop["t_ttfb"] = time.monotonic()
                        hop["status"] = resp.status
                        hop["timing"] = observability.parse_server_timing(
                            resp.getheader(HDR_SERVER_TIMING) or "")
                        streaming = (resp.status == 200
                                     and "text/event-stream"
                                     in (resp.getheader("Content-Type")
                                         or ""))
                        if not streaming:
                            payload = (resp.status,
                                       (yield from resp.read_all(
                                           self._body_deadline())),
                                       self._relay_headers(resp))
                    except (OSError, faults.FaultInjected) as e:
                        replica.mark_conn_failure()
                        st._m_upstream_errors.inc(replica=replica.name)
                        if st.flight is not None:
                            st.flight.record("upstream_error",
                                             replica=replica.name,
                                             request_id=self._rid,
                                             error=repr(e)[:200])
                        if attempts < st.retry_budget:
                            attempts += 1
                            st._m_retries.inc()
                            continue
                        hop["error"] = "upstream"
                        hop["status"] = 502
                        yield from self._error(
                            502, f"upstream {replica.name} failed: {e}")
                        return
                    if resp.status == 503:
                        # draining or scheduler-crashed: out of rotation
                        # NOW (don't wait for the probe), retry elsewhere
                        replica.mark_unready()
                        st._m_upstream_errors.inc(replica=replica.name)
                        if st.flight is not None:
                            st.flight.record("upstream_503",
                                             replica=replica.name,
                                             request_id=self._rid)
                        if attempts < st.retry_budget:
                            attempts += 1
                            st._m_retries.inc()
                            last_503 = payload
                            continue
                        yield from self._relay_buffered(*payload)
                        return
                    # a usable response (200/429/504/4xx/...): this hop is
                    # done retrying — forward it verbatim
                    replica.mark_conn_success()
                    if streaming:
                        handed_off = True  # the relay closes the socket
                        yield from self._relay_sse(resp, up, replica)
                    else:
                        if resp.reusable and not up.buf:
                            # fully-drained framed body on a keep-alive
                            # socket: back to the pool for the next hop
                            self.server.pool.put(replica.host, replica.port,
                                                 up.sock)
                            handed_off = True
                        yield from self._relay_buffered(*payload)
                    if resp.status == 200 and affinity_hashes:
                        st.affinity.record(affinity_hashes, replica.name)
                    return
                finally:
                    # runs on every exit AND every retry `continue`: the
                    # in-flight count and the upstream socket never leak
                    replica.end()
                    if up is not None and not handed_off:
                        up.close()
        finally:
            self._finish_proxy(hop)

    def _finish_proxy(self, hop: dict) -> None:
        """Close out one proxied request: per-hop attribution histograms
        (the router's wall time minus the phases the replica claimed via
        Server-Timing) and the router-side trace spans, flow-arrowed to
        the replica's track. A hop that never produced a usable response
        — including a replica killed mid-request — closes its span with
        ``error=true`` so the orphan is visible, not silently absent."""
        st = self.state
        t_end = time.monotonic()
        timing = hop["timing"]
        if hop["t_conn"] is not None and hop["t_ttfb"] is not None:
            st._m_hop.observe((hop["t_ttfb"] - hop["t_conn"]) * 1e3,
                              phase="connect")
            if "queue" in timing:
                st._m_hop.observe(timing["queue"], phase="upstream_queue")
            if "prefill" in timing or "decode" in timing:
                st._m_hop.observe(timing.get("prefill", 0.0)
                                  + timing.get("decode", 0.0),
                                  phase="upstream_compute")
            st._m_hop.observe((t_end - hop["t_ttfb"]) * 1e3, phase="stream")
        if observability.trace_path() is None:
            return
        pid = os.getpid()
        tid = self._span_id
        us = observability.mono_to_us
        span_args = {"request_id": self._rid, "replica": hop["replica"],
                     "status": hop["status"]}
        if hop["error"] is not None:
            span_args["error"] = True
            span_args["error_kind"] = hop["error"]
        events = [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": f"router {self._rid}"}},
            {"name": "router_proxy", "ph": "X", "pid": pid, "tid": tid,
             "ts": us(self._t_begin),
             "dur": max(1, us(t_end) - us(self._t_begin)),
             "cat": "router", "args": span_args},
        ]
        if hop["t_conn"] is not None:
            t_fb = hop["t_ttfb"] if hop["t_ttfb"] is not None else t_end
            events.append(
                {"name": "connect", "ph": "X", "pid": pid, "tid": tid,
                 "ts": us(hop["t_conn"]),
                 "dur": max(1, us(t_fb) - us(hop["t_conn"])),
                 "cat": "router"})
            events.append(observability.flow_start_event(
                self._parent_value, tid, hop["t_conn"]))
            if hop["t_ttfb"] is not None:
                events.append(
                    {"name": "stream", "ph": "X", "pid": pid, "tid": tid,
                     "ts": us(hop["t_ttfb"]),
                     "dur": max(1, us(t_end) - us(hop["t_ttfb"])),
                     "cat": "router"})
        observability.emit_trace_events(events)

    @staticmethod
    def _relay_headers(resp) -> dict:
        """Upstream headers worth forwarding verbatim. Retry-After carries
        the replica's backoff hint on 429/503; Server-Timing carries the
        replica's phase split (the router appends its own total as a
        second header — HTTP merges repeats); X-Request-Id is OURS (the
        replica echoes the same id we sent, so no conflict)."""
        out = {}
        for k in ("Content-Type", "Retry-After", HDR_SERVER_TIMING):
            v = resp.getheader(k)
            if v is not None:
                out[k] = v
        return out

    @loop_callback
    def _relay_buffered(self, status: int, body: bytes, headers: dict):
        # client vanishing before the (already complete) body lands counts
        # in _send and unwinds run(): nothing upstream to cancel or retry
        yield from self._respond(status, list(headers.items()), body)

    @loop_callback
    def _relay_sse(self, resp, up, replica):
        """SSE passthrough: relay upstream bytes to the client as they
        arrive (one chunk in hand at a time — the client write completes
        before the next upstream read, which IS the backpressure: a slow
        client pauses its upstream instead of growing router RSS) —
        byte-identical bodies.

        The one stateful obligation: when the CLIENT disconnects
        mid-stream, close the UPSTREAM connection immediately — the
        replica's cancel-on-disconnect frees the decode slot within one
        chunk. Closing at generator GC instead would keep the dead
        stream decoding for its full completion.

        With ``--ckpt-interval`` > 0 the relay is RESUMABLE instead:
        event-aligned forwarding that strips ``dllama-ckpt`` control
        frames into the checkpoint store and, on upstream death without
        ``[DONE]`` — including a SILENT upstream past the inter-byte
        stall budget — splices a sibling's /v1/kv/resume stream into
        this same client connection (:meth:`_relay_sse_resumable`)."""
        self.close_after = True  # SSE is EOF-delimited toward the client
        try:
            hs = [("Content-Type",
                   resp.getheader("Content-Type") or "text/event-stream"),
                  ("Cache-Control", "no-cache"),
                  ("Connection", "close"),
                  (HDR_REQUEST_ID, self._rid)]
            upstream_timing = resp.getheader(HDR_SERVER_TIMING)
            if upstream_timing:
                hs.append((HDR_SERVER_TIMING, upstream_timing))
            hs.append((HDR_SERVER_TIMING, self._server_timing()))
            self._count(200)
            yield from self._send(evloop.response_bytes(200, hs))
            if self.state.ckpt_interval > 0:
                yield from self._relay_sse_resumable(resp, up, replica)
                return
            while True:
                try:
                    chunk = yield from resp.read_some(self._stall_deadline())
                except OSError:
                    break  # upstream died/stalled mid-stream: the partial
                    #        body and missing [DONE] are the client's
                    #        truncation signal (no resume without ckpts)
                if not chunk:
                    break
                try:
                    yield from self._send(chunk)
                except ClientGone:
                    break
        finally:
            # the immediacy guarantee: upstream socket down NOW, on every
            # exit path (client gone, upstream EOF, relay error)
            up.close()

    @loop_callback
    def _relay_sse_resumable(self, resp, up, replica):
        """The failover relay (client headers already sent): forward the
        upstream stream EVENT-aligned, stripping ``dllama-ckpt`` control
        frames into the checkpoint store, and treat an upstream end
        without ``[DONE]`` as a mid-stream death — clean EOF/torn read
        (cause ``eof``) and an upstream SILENT past the inter-byte
        ``--stall-timeout`` budget (cause ``stall``) take the same resume
        path, distinguished only in the outcome label. One death resumes
        on a sibling via :meth:`_resume_stream` — the continued stream's
        first ``forwarded - offset`` bytes are what the client already
        holds (bit-identical regeneration from the checkpoint), so they
        are discarded and the splice leaves no repeat and no gap. A
        SECOND death, or any fallback-matrix row, terminates cleanly: a
        typed SSE ``error`` event + ``[DONE]`` instead of a bare TCP cut.

        The stall verdict gets one grace read (STALL_DRAIN_GRACE_S):
        bytes already in flight at the expiry instant — including a
        ``[DONE]`` that arrived in the same read as the budget ran out —
        are delivered and FORGIVE the stall; only true silence fails
        over. Without the grace, that race would fail over a stream the
        client was one event away from completing."""
        st = self.state
        rid = self._rid
        forwarded = 0  # client-visible bytes forwarded (event-aligned —
        #                exactly the replica writer's bytes_emitted count)
        skip = 0  # resumed-stream prefix the client already holds
        saw_done = False
        client_gone = False
        owned = False  # True once `replica` was begin()-ed by a resume
        #                (the original caller begin/ends the FIRST hop)
        try:
            while True:
                scanner = observability.SSEScanner()
                cause = "eof"
                while True:  # one upstream's lifetime
                    try:
                        faults.fire("relay_stall")
                        chunk = yield from resp.read_some(
                            self._stall_deadline())
                    except (evloop.LoopTimeout, faults.FaultInjected):
                        # stall verdict — grace drain first: bytes already
                        # in flight beat the expired budget
                        chunk = resp.try_read_now()
                        if not chunk:
                            try:
                                chunk = yield from resp.read_some(
                                    time.monotonic()
                                    + evloop.STALL_DRAIN_GRACE_S)
                            except OSError:
                                chunk = b""
                        if not chunk:
                            cause = "stall"
                            break
                        # data surfaced: forgive the stall and continue
                        # with a fresh inter-byte budget
                    except OSError:
                        chunk = b""  # a torn read is a death, same as EOF
                    if not chunk:
                        break
                    for ev in scanner.feed(chunk):
                        fields = observability.sse_event_fields(ev)
                        if fields.get("event") == _CKPT_EVENT_B:
                            off, _, b64 = fields.get(
                                "data", b"").partition(b" ")
                            try:
                                st.ckpt_store.put(rid, base64.b64decode(b64),
                                                  int(off), replica.name)
                            except ValueError:
                                pass  # malformed frame: keep the last
                                #       good checkpoint
                            continue
                        if skip:  # resumed prefix the client already holds
                            if skip >= len(ev):
                                skip -= len(ev)
                                continue
                            ev = ev[skip:]
                            skip = 0
                        if fields.get("data", b"").strip() == b"[DONE]":
                            saw_done = True
                        if not client_gone:
                            try:
                                yield from self._send(ev)
                            except ClientGone:
                                client_gone = True
                            else:
                                forwarded += len(ev)
                    if client_gone or saw_done:
                        break
                if saw_done or client_gone:
                    return
                # upstream ended without [DONE]: a mid-stream death
                replica.mark_conn_failure()
                st._m_upstream_errors.inc(replica=replica.name)
                if st.flight is not None:
                    st.flight.record("upstream_stream_death",
                                     replica=replica.name, request_id=rid,
                                     forwarded=forwarded, cause=cause)
                if owned:
                    # second death during resume: the fallback matrix says
                    # terminate cleanly, don't chase replicas forever
                    self._account_resume(
                        "exhausted", {"dead": replica.name,
                                      "forwarded": forwarded},
                        time.monotonic())
                    yield from self._fail_stream(
                        "upstream replica died again after a "
                        "resume; stream incomplete")
                    return
                got = yield from self._resume_stream(rid, replica,
                                                     forwarded, cause)
                if isinstance(got, str):
                    yield from self._fail_stream(got)  # already accounted
                    return
                up.close()  # the dead upstream's socket
                resp, up, replica, offset = got
                skip = forwarded - offset
                owned = True
        finally:
            up.close()
            if owned:
                replica.end()
            st.ckpt_store.pop(rid)

    @loop_callback
    def _fail_stream(self, message: str):
        # the torn-stream obligation: resume exhausted -> the client gets
        # a typed terminal error event and a [DONE], so "torn" is
        # distinguishable from "complete" without timeout heuristics
        try:
            yield from self._send(
                b"data: " + json.dumps(
                    {"error": {"message": message, "type": "upstream_error",
                               "code": 502}}).encode()
                + b"\n\ndata: [DONE]\n\n")
        except ClientGone:
            pass  # the client is gone; there is no one left to tell

    @loop_callback
    def _resume_stream(self, rid: str, dead, forwarded: int,
                       cause: str = "eof"):
        """One resume orchestration after ``dead`` died mid-SSE at byte
        ``forwarded``. Fires the ``resume`` seam at the decision point.

        Returns ``(resp, up, replica, offset)`` on success — outcome
        "ok" (or "stall" when the death verdict came from the inter-byte
        stall budget), the sibling's in-flight count held (begin without
        end) until the relay finishes — or a client-facing failure
        message string with the fallback-matrix outcome (no_ckpt /
        stale_ckpt / no_replica / admit_failed / injected) already
        accounted."""
        st = self.state
        outcome = "no_ckpt"
        detail: dict = {"dead": dead.name, "forwarded": forwarded,
                        "cause": cause}
        t0 = time.monotonic()
        try:
            try:
                faults.fire("resume")
            except faults.FaultInjected:
                outcome = "injected"
                return "resume fault injected; stream incomplete"
            entry = st.ckpt_store.get(rid)
            if entry is None:
                outcome = "no_ckpt"
                return ("upstream replica died mid-stream and no "
                        "checkpoint exists; stream incomplete")
            offset = int(entry["offset"])
            detail["offset"] = offset
            if offset > forwarded:
                # the snapshot claims MORE bytes than the client holds:
                # splicing would leave a gap — refuse rather than corrupt
                outcome = "stale_ckpt"
                return ("checkpoint is ahead of the forwarded stream; "
                        "stream incomplete")
            tried = {dead.name}
            attempted = 0
            for _ in range(1 + st.retry_budget):
                try:
                    sibling, _ = st.pick([], exclude=tried)
                except (NoReplicaAvailable, faults.FaultInjected):
                    break
                tried.add(sibling.name)
                attempted += 1
                detail["sibling"] = sibling.name
                sibling.begin()
                ok = False
                up = None
                try:
                    try:
                        faults.fire("proxy_upstream")
                        up = yield from evloop.open_upstream(
                            self.server.pool, sibling.host, sibling.port,
                            self._deadline(st.connect_timeout_s))
                        headers = self._upstream_headers()
                        headers["Content-Type"] = kv_transfer.CONTENT_TYPE
                        yield from up.request(
                            "POST", "/v1/kv/resume", headers,
                            entry["payload"],
                            self._deadline(st.connect_timeout_s))
                        resp = yield from up.get_response(
                            self._first_byte_deadline())
                    except (OSError, faults.FaultInjected) as e:
                        sibling.mark_conn_failure()
                        st._m_upstream_errors.inc(replica=sibling.name)
                        detail["error"] = repr(e)[:200]
                        continue
                    if (resp.status != 200 or resp.getheader(
                            HDR_RESUME_OFFSET) is None):
                        # 503 = draining/full pool, 422 = the checkpoint
                        # itself was rejected; either way THIS sibling did
                        # no decode work — try the next one
                        if resp.status == 503:
                            sibling.mark_unready()
                        st._m_upstream_errors.inc(replica=sibling.name)
                        detail["status"] = resp.status
                        continue
                    sibling.mark_conn_success()
                    # a successful resume after a STALL death is the stall
                    # outcome — the row BENCH_C10K asserts on
                    outcome = "stall" if cause == "stall" else "ok"
                    ok = True
                    return resp, up, sibling, offset
                finally:
                    if not ok:
                        sibling.end()
                        if up is not None:
                            up.close()
            outcome = "admit_failed" if attempted else "no_replica"
            return ("no sibling replica accepted the checkpoint; "
                    "stream incomplete" if attempted else
                    "no sibling replica available for resume; "
                    "stream incomplete")
        finally:
            self._account_resume(outcome, detail, t0)

    def _account_resume(self, outcome: str, detail: dict, t0: float) -> None:
        """Every resume decision — ok or any fallback-matrix row — lands
        in the counter, the flight ring, and (when tracing) a
        ``router_resume`` hop span, mirroring the migrate accounting."""
        st = self.state
        st._m_resumes.inc(outcome=outcome)
        if st.flight is not None:
            st.flight.record("resume", request_id=self._rid,
                             outcome=outcome, **detail)
        if observability.trace_path() is not None:
            us = observability.mono_to_us
            observability.emit_trace_events([
                {"name": "router_resume", "ph": "X",
                 "pid": os.getpid(), "tid": self._span_id,
                 "ts": us(t0),
                 "dur": max(1, us(time.monotonic()) - us(t0)),
                 "cat": "router",
                 "args": dict(detail, request_id=self._rid,
                              outcome=outcome)},
            ])


def create_router_server(state: RouterState, host: str = "0.0.0.0",
                         port: int = 9900):
    """The router's event-loop front door: one selectors loop carrying
    every client connection as a coroutine (same server_address /
    serve_forever / shutdown / server_close surface the threaded server
    had). Admission runs at accept time, BEFORE any per-connection state
    exists: the ``conn_accept`` seam fires first (injectable shed), then
    ``--max-conns`` sheds with a canned 503 + Retry-After — an overloaded
    router refuses cheaply instead of degrading every live stream."""
    shed_body = json.dumps(
        {"error": {"message": "router at connection capacity",
                   "type": "server_error"}}).encode()
    retry_after = str(max(1, int(round(max(1.0, state.probe_interval_s)))))
    shed_response = evloop.response_bytes(503, [
        ("Content-Type", "application/json"),
        ("Retry-After", retry_after),
        ("Content-Length", str(len(shed_body))),
        ("Connection", "close"),
    ], shed_body)

    def gate(server):
        try:
            faults.fire("conn_accept")
        except faults.FaultInjected:
            return "injected"
        if state.max_conns and server.open_conns >= state.max_conns:
            return "max_conns"
        return None

    def conn_handler(server, sock, addr):
        return RouterConnection(server, state, sock, addr).run()

    srv = evloop.EventLoopServer(
        (host, port), conn_handler, gate=gate,
        shed_response=shed_response,
        on_shed=lambda reason: state._m_sheds.inc(reason=reason))
    srv.pool = evloop.UpstreamPool()
    state._m_conns.set_function(lambda: float(srv.open_conns))
    return srv


def state_from_args(args, replica_addrs: list) -> RouterState:
    """RouterState from parsed `cli router`/`cli fleet` flags + a list of
    "host:port" strings."""
    replicas = []
    for addr in replica_addrs:
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise SystemExit(f"bad --replica {addr!r}: want HOST:PORT")
        replicas.append(Replica(host, int(port)))
    if not replicas:
        raise SystemExit("router needs at least one --replica HOST:PORT")
    return RouterState(
        replicas,
        retry_budget=getattr(args, "retry_budget", 2),
        probe_interval_s=getattr(args, "probe_interval", 1.0),
        connect_timeout_s=getattr(args, "connect_timeout", 2.0),
        upstream_timeout_s=getattr(args, "upstream_timeout", 0.0),
        affinity_block=getattr(args, "affinity_block", 256),
        kv_wire=getattr(args, "kv_wire", "f32") or "f32",
        ckpt_interval=getattr(args, "ckpt_interval", 32),
        ckpt_ttl_s=getattr(args, "ckpt_ttl", 600.0),
        ts_interval=getattr(args, "ts_interval", 1.0),
        max_conns=getattr(args, "max_conns", 0),
        header_timeout_s=getattr(args, "header_timeout", 10.0),
        first_byte_timeout_s=getattr(args, "first_byte_timeout", 0.0),
        stall_timeout_s=getattr(args, "stall_timeout", 0.0),
        client_stall_timeout_s=getattr(args, "client_stall_timeout", 30.0),
        probe_read_timeout_s=getattr(args, "probe_read_timeout", 2.0),
    )


def run_router(args) -> None:
    """``cli router``: front a fleet of already-running replicas. No jax,
    no model artifacts — the router is pure stdlib networking and starts
    in milliseconds."""
    state = state_from_args(args, args.replica)
    observability.emit_process_name("router")
    state.probe_once()  # synchronous first round: start with a real picture
    state.start_probes()
    srv = create_router_server(state, host=args.host, port=args.port)
    print(f"🛰️  router on {args.host}:{args.port} -> "
          f"{', '.join(r.name for r in state.replicas)} "
          f"(affinity block {state.affinity_block}B, "
          f"retry budget {state.retry_budget})")
    try:
        srv.serve_forever()
    finally:
        state.stop_probes()
