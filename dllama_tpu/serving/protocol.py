"""Shared wire-contract constants for the dllama fleet.

Every string that crosses a process boundary — hop headers, SSE event
names, DKV1 snapshot header fields, the KV content type — lives HERE
and only here.  Writers and readers both import from this module, so a
one-sided rename is an ImportError / NameError instead of a silent
fleet-wide desync.  dllama-check's PROTO-00x passes enforce the rest:

* PROTO-001 — DKV1 fields written by ``encode_snapshot`` vs parsed by
  ``decode_snapshot`` vs the ``DKV1_HEADER_FIELDS`` registry below.
* PROTO-002 — SSE event names emitted vs scanned; raw event literals
  outside this module are findings.
* PROTO-003 — hop header strings minted vs read; raw ``X-Dllama-*`` /
  ``X-Request-Id`` literals outside this module are findings.
* PROTO-004 — metric names consumed somewhere in the package must be
  registered via ``counter()``/``gauge()``/``histogram()``; and
  ``cli.py`` (the `top`/`explain`/`snapshot` consumers, which scrape the
  wire rather than share a registry) may not spell a raw ``dllama_*``
  literal at all — it imports the ``MET_*`` constants below, so the
  dashboards can never silently desync from the registry.

Keep every value a plain string literal (the analyzer reads this file
with ``ast``, it never imports it).  Derive bytes at the use site with
``.encode()``.
"""

# --------------------------------------------------------------------------
# Hop headers (router <-> replica <-> client).
# --------------------------------------------------------------------------

HDR_REQUEST_ID = "X-Request-Id"
HDR_PARENT_SPAN = "X-Dllama-Parent-Span"
HDR_CKPT = "X-Dllama-Ckpt"
HDR_CKPT_WIRE = "X-Dllama-Ckpt-Wire"
HDR_CLASS = "X-Dllama-Class"
HDR_RESUME_OFFSET = "X-Dllama-Resume-Offset"
HDR_SERVER_TIMING = "Server-Timing"

#: Every header the fleet mints or reads on a hop.  PROTO-003 checks this
#: tuple against the HDR_* constants above and against actual use.
HOP_HEADERS = (
    HDR_REQUEST_ID,
    HDR_PARENT_SPAN,
    HDR_CKPT,
    HDR_CKPT_WIRE,
    HDR_CLASS,
    HDR_RESUME_OFFSET,
    HDR_SERVER_TIMING,
)

# --------------------------------------------------------------------------
# SSE control frames (in-band on /v1/completions streams).
# --------------------------------------------------------------------------

SSE_EVENT_CKPT = "dllama-ckpt"

#: Every named SSE event the fleet emits or scans for.  PROTO-002 checks
#: each one has both an emitter and a scanner module.
SSE_EVENTS = (
    SSE_EVENT_CKPT,
)

# --------------------------------------------------------------------------
# DKV1 snapshot codec (serving/kv_transfer.py).
# --------------------------------------------------------------------------

DKV1_MAGIC = b"DKV1"
KV_CONTENT_TYPE = "application/x-dllama-kv"
WIRE_MODES = ("f32", "q80", "q80+f32")

#: Scalar header fields written/parsed in one loop on both sides.
DKV1_SCALARS = (
    "page_tokens",
    "n_blocks",
    "plen",
    "pos",
    "token",
    "room",
    "budget",
    "offered",
    "emitted",
)

#: Structural fields always present in a DKV1 JSON header.
DKV1_BASE_FIELDS = (
    "v",
    "mode",
    "tokens",
    "prompt",
    "keys",
    "temp",
    "topp",
    "stop_tokens",
    "n_leaves",
    "leaf_shapes",
    "extra",
)

#: Fields the encoder writes conditionally; the decoder must still parse
#: them (with a default) or resumed sessions silently lose state.
DKV1_OPTIONAL_FIELDS = (
    "stop_state",
)

#: The full header contract.  PROTO-001 checks encode/decode against it.
DKV1_HEADER_FIELDS = DKV1_BASE_FIELDS + DKV1_SCALARS + DKV1_OPTIONAL_FIELDS

# --------------------------------------------------------------------------
# Metric families read across a process boundary (cli top / explain /
# snapshot scrape them off /metrics, /metrics/fleet and /metrics/history —
# they never share a registry with the process that registered them).
# --------------------------------------------------------------------------

MET_HTTP_REQUESTS = "dllama_http_requests_total"
MET_TTFT_MS = "dllama_ttft_ms"
MET_TPOT_MS = "dllama_tpot_ms"
MET_KV_TRANSFER_BYTES = "dllama_kv_transfer_bytes_total"
MET_CLASS_TTFT_MS = "dllama_class_ttft_ms"
MET_CLASS_TPOT_MS = "dllama_class_tpot_ms"
MET_CLASS_QUEUE_DEPTH = "dllama_class_queue_depth"
MET_CLASS_RESIDENT_ROWS = "dllama_class_resident_rows"
MET_TS_SAMPLES = "dllama_ts_samples_total"
MET_ALERTS = "dllama_alerts_total"
MET_FEDERATE_SKIPPED = "dllama_router_federate_skipped_total"
MET_FLEET_REPLICAS = "dllama_fleet_replicas"
MET_SCALE_EVENTS = "dllama_fleet_scale_events_total"
MET_POLICY_EVALS = "dllama_fleet_policy_evals_total"
MET_CKPT_EXPIRED = "dllama_router_ckpt_expired_total"
MET_TP_REDUCE_CHUNKS = "dllama_tp_reduce_chunks_total"

#: Label names of the ``dllama_tp_wire_info`` info-gauge (value 1, identity
#: in the labels): the resolved gather wire, overlap mode, and row-parallel
#: reduce mode.  The server registers with exactly these labels and
#: BENCH_REDUCE / fleet dashboards read them back off /metrics, so the
#: tuple lives here with the other cross-process names.
TP_WIRE_INFO_LABELS = ("tp_wire", "tp_overlap", "tp_reduce")

#: Every family a cross-process consumer reads.  PROTO-004's cli.py pass
#: checks this tuple stays registered AND that cli.py spells no family
#: outside it.
WIRE_METRICS = (
    MET_HTTP_REQUESTS,
    MET_TTFT_MS,
    MET_TPOT_MS,
    MET_KV_TRANSFER_BYTES,
    MET_CLASS_TTFT_MS,
    MET_CLASS_TPOT_MS,
    MET_CLASS_QUEUE_DEPTH,
    MET_CLASS_RESIDENT_ROWS,
    MET_TS_SAMPLES,
    MET_ALERTS,
    MET_FEDERATE_SKIPPED,
    MET_FLEET_REPLICAS,
    MET_SCALE_EVENTS,
    MET_POLICY_EVALS,
    MET_CKPT_EXPIRED,
    MET_TP_REDUCE_CHUNKS,
)
