"""KV page-stream wire codec for disaggregated prefill/decode serving.

A migrating row leaves a prefill replica as a :meth:`BatchSession.export_row
<dllama_tpu.runtime.generate.BatchSession.export_row>` snapshot — page
payloads plus the carried decode state — and arrives at a decode replica as
the byte stream this module frames:

``MAGIC | u32 len | header JSON | u32 crc32`` followed by one
``u32 len | payload | u32 crc32`` frame per (arena leaf, page). The header
carries everything :meth:`admit_from_export` and the serving layer need
(geometry, sampler-chain state, budget accounting, the prompt tokens, and
an opaque ``extra`` dict for HTTP-level fields); the frames carry each
page's VALID token prefix only — the last, partially-filled page ships
short, and the decoder zero-fills the never-attended tail.

Three wire modes:

* ``f32`` — bit-exact: pages travel as raw float32 (a superset of the
  bf16/f32 arena dtypes), so a migrated row's stream is token-for-token
  the solo stream.
* ``q80`` — each page payload is flattened and block-quantized with the
  repo's Q80 codec (:mod:`dllama_tpu.quants.blocks`: 32-element blocks,
  f16 delta + int8 quants — 34 bytes per 128) for ~3.76x fewer wire
  bytes. Lossy but error-bounded: :func:`q80_error_bound` derives the
  per-element bound from the same quant model, and the tolerance test
  gates the codec against it.
* ``q80+f32`` — hybrid: FULL pages ship q80, the partially-filled tail
  page ships bit-exact f32. The tail page is the only KV the very next
  decode steps attend to with fresh queries, so shipping it exact keeps
  greedy continuation bit-identical in practice at near-q80 wire cost
  (the full-page error bound still applies to the q80 frames). The
  frame split is derived from the header geometry on both sides —
  ``ntok == page_tokens`` means q80 — so no per-frame mode byte rides
  the wire.

Header versions: ``v=1`` is the original header; ``v=2`` adds the
optional ``stop_state`` field carrying a ``StopDetector``'s scanback
state (``{"stops": [...], "hold": "...", "stopped": false}``) so
stop-string sessions can migrate/resume. Decoders accept both; a v1
stream simply has ``stop_state=None``, and anything newer than v2 is
rejected with a reason (``TransferError``) rather than half-admitted.

Every length is read exactly and every frame CRC-checked; a short read or
checksum mismatch raises :class:`TransferError` — a torn stream can never
half-admit a row. Dependency-free beyond numpy (stdlib ``json``/``zlib``),
so the router can decode headers without jax."""

from __future__ import annotations

import io
import json
import zlib
from typing import Optional

import numpy as np

from ..quants.blocks import QK, dequantize_q80, quantize_q80
# wire-contract strings live in serving/protocol.py (PROTO-001 checks the
# encode/decode field sets against DKV1_HEADER_FIELDS there); the names
# below stay re-exported for existing importers
from .protocol import DKV1_MAGIC as MAGIC
from .protocol import DKV1_SCALARS as _SCALARS
from .protocol import KV_CONTENT_TYPE as CONTENT_TYPE
from .protocol import WIRE_MODES


class TransferError(RuntimeError):
    """A KV page stream that cannot be trusted: truncated mid-frame, CRC
    mismatch, bad magic, or a header that fails validation. The importer
    treats every one the same way — reject the whole transfer and let the
    caller fall back to re-prefilling; a torn stream never half-admits."""


def q80_error_bound(x: np.ndarray) -> float:
    """Max absolute per-element error the Q80 wire may introduce on ``x``,
    derived from the quant model itself: values quantize in 32-element
    blocks with ``delta = f16(absmax/127)``, round-half-even — so the
    reconstruction error is at most ``delta/2`` per block plus the f16
    rounding of delta (relative ``2**-11``) scaled by the +-127 quant
    range. Tests assert the actual round-trip error under this bound.

    Under the hybrid ``q80+f32`` wire this bound applies only to FULL
    pages — the partial tail page travels f32 and round-trips exactly
    (error 0), which is what keeps greedy continuation bit-identical."""
    flat = np.asarray(x, np.float32).reshape(-1)
    if flat.size == 0:
        return 0.0
    pad = (-flat.size) % QK
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    absmax = np.abs(flat.reshape(-1, QK)).max(axis=1)
    delta = (absmax / 127.0).astype(np.float16).astype(np.float32)
    return float(delta.max() * (0.5 + 127.0 * 2.0 ** -11))


def _q80_encode(flat: np.ndarray) -> bytes:
    pad = (-flat.size) % QK
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    return quantize_q80(flat).tobytes()


def _q80_decode(payload: bytes, n: int) -> np.ndarray:
    padded = n + (-n) % QK
    raw = np.frombuffer(payload, np.uint8)
    want = (padded // QK) * 34
    if raw.size != want:
        raise TransferError(
            f"q80 frame size {raw.size} != expected {want}")
    return dequantize_q80(raw, padded)[:n]


def _frame_is_f32(mode: str, ntok: int, page: int) -> bool:
    """Per-frame wire choice, derived identically on both sides: hybrid
    ships full pages q80 and the partial tail page bit-exact f32."""
    return mode == "f32" or (mode == "q80+f32" and ntok < page)


def encode_snapshot(snap: dict, prompt_tokens, mode: str = "f32",
                    extra: Optional[dict] = None,
                    stop_state: Optional[dict] = None) -> bytes:
    """Frame an ``export_row`` snapshot (plus the row's prompt and an
    opaque ``extra`` dict for the serving layer) into one byte stream.
    ``stop_state`` (a StopDetector's ``{"stops", "hold", "stopped"}``
    scanback state) bumps the header to v=2 so pre-v2 importers reject
    the stream with a reason instead of silently dropping the stops."""
    if mode not in WIRE_MODES:
        raise ValueError(f"unknown wire mode {mode!r} (know {WIRE_MODES})")
    leaves = snap["leaves"]
    page = int(snap["page_tokens"])
    nblk = int(snap["n_blocks"])
    # positions [0, pos) are written KV; the rest of the last page is
    # garbage the decode overwrites before attending — don't ship it
    tokens = max(0, min(int(snap["pos"]), nblk * page))
    header = {"v": 2 if stop_state is not None else 1,
              "mode": mode, "tokens": tokens,
              "prompt": [int(t) for t in prompt_tokens],
              "keys": [int(k) for k in snap["keys"]],
              "temp": float(snap["temp"]), "topp": float(snap["topp"]),
              "stop_tokens": [int(t) for t in snap["stop_tokens"]],
              "n_leaves": len(leaves),
              # per-leaf block shape [L, page, kv, hd] (leaves arrive as
              # [L, n_blocks, page, kv, hd]; the page axis is reframed)
              "leaf_shapes": [[int(lf.shape[0])] + list(lf.shape[2:])
                              for lf in leaves],
              "extra": extra or {}}
    if stop_state is not None:
        header["stop_state"] = {
            "stops": [str(s) for s in stop_state.get("stops", [])],
            "hold": str(stop_state.get("hold", "")),
            "stopped": bool(stop_state.get("stopped", False))}
    for k in _SCALARS:
        header[k] = int(snap[k])
    hdr = json.dumps(header, separators=(",", ":")).encode()
    out = io.BytesIO()
    out.write(MAGIC)
    out.write(len(hdr).to_bytes(4, "big"))
    out.write(hdr)
    out.write(zlib.crc32(hdr).to_bytes(4, "big"))
    for leaf in leaves:
        lf = np.asarray(leaf, np.float32)  # exact for bf16/f32 arenas
        for b in range(nblk):
            ntok = max(0, min(tokens - b * page, page))
            x = np.ascontiguousarray(lf[:, b, :ntok])
            flat = x.reshape(-1)
            payload = (flat.tobytes() if _frame_is_f32(mode, ntok, page)
                       else _q80_encode(flat))
            out.write(len(payload).to_bytes(4, "big"))
            out.write(payload)
            out.write(zlib.crc32(payload).to_bytes(4, "big"))
    return out.getvalue()


def _read_exact(rd, n: int, what: str) -> bytes:
    buf = rd.read(n)
    if buf is None or len(buf) != n:
        raise TransferError(
            f"torn stream: short read of {what} "
            f"({0 if buf is None else len(buf)}/{n} bytes)")
    return buf


def decode_snapshot(data) -> dict:
    """Parse a framed page stream back into an ``admit_from_export``-shaped
    snapshot (leaves float32, zero-filled past each page's valid tokens)
    with ``prompt``, ``mode`` and ``extra`` attached. ``data`` is a bytes
    object or a binary file-like. Raises :class:`TransferError` on any
    truncation, CRC mismatch, or malformed header."""
    rd = io.BytesIO(data) if isinstance(data, (bytes, bytearray)) else data
    if _read_exact(rd, len(MAGIC), "magic") != MAGIC:
        raise TransferError("bad magic: not a KV page stream")
    hlen = int.from_bytes(_read_exact(rd, 4, "header length"), "big")
    if hlen <= 0 or hlen > 1 << 24:
        raise TransferError(f"implausible header length {hlen}")
    hdr = _read_exact(rd, hlen, "header")
    crc = int.from_bytes(_read_exact(rd, 4, "header crc"), "big")
    if zlib.crc32(hdr) != crc:
        raise TransferError("header crc mismatch")
    try:
        header = json.loads(hdr.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise TransferError(f"unparseable header: {e}") from None
    mode = header.get("mode")
    if header.get("v") not in (1, 2) or mode not in WIRE_MODES:
        raise TransferError(
            f"unsupported stream (v={header.get('v')!r}, mode={mode!r})")
    stop_state = header.get("stop_state")
    if stop_state is not None:
        if (not isinstance(stop_state, dict)
                or not isinstance(stop_state.get("stops"), list)):
            raise TransferError("malformed stop_state in v2 header")
        stop_state = {"stops": [str(s) for s in stop_state["stops"]],
                      "hold": str(stop_state.get("hold", "")),
                      "stopped": bool(stop_state.get("stopped", False))}
    try:
        page = int(header["page_tokens"])
        nblk = int(header["n_blocks"])
        tokens = int(header["tokens"])
        n_leaves = int(header["n_leaves"])
        shapes = [tuple(int(d) for d in s) for s in header["leaf_shapes"]]
        prompt = [int(t) for t in header["prompt"]]
    except (KeyError, TypeError, ValueError) as e:
        raise TransferError(f"malformed header: {e}") from None
    if (page < 1 or nblk < 0 or n_leaves != len(shapes)
            or len(prompt) != int(header["plen"])):
        raise TransferError("inconsistent header geometry")
    leaves = []
    for shape in shapes:
        # block shape [L, page, kv, hd]; the wire frames ship each page's
        # valid token prefix [L, ntok, kv, hd]
        if len(shape) != 4 or shape[1] != page:
            raise TransferError(f"bad leaf block shape {shape}")
        L, _, kv, hd = shape
        lf = np.zeros((L, nblk, page, kv, hd), np.float32)
        for b in range(nblk):
            ntok = max(0, min(tokens - b * page, page))
            n = L * ntok * kv * hd
            payload_len = int.from_bytes(
                _read_exact(rd, 4, "frame length"), "big")
            payload = _read_exact(rd, payload_len, "frame payload")
            fcrc = int.from_bytes(_read_exact(rd, 4, "frame crc"), "big")
            if zlib.crc32(payload) != fcrc:
                raise TransferError(f"frame crc mismatch at block {b}")
            if _frame_is_f32(mode, ntok, page):
                if payload_len != 4 * n:
                    raise TransferError(
                        f"f32 frame size {payload_len} != {4 * n}")
                flat = np.frombuffer(payload, np.float32).copy()
            else:
                flat = _q80_decode(payload, n)
            if ntok:
                lf[:, b, :ntok] = flat.reshape(L, ntok, kv, hd)
        leaves.append(lf)
    snap = {k: int(header[k]) for k in _SCALARS}
    snap["keys"] = [int(k) for k in header["keys"]]
    snap["temp"] = float(header["temp"])
    snap["topp"] = float(header["topp"])
    snap["stop_tokens"] = [int(t) for t in header["stop_tokens"]]
    snap["leaves"] = leaves
    snap["prompt"] = prompt
    snap["mode"] = mode
    snap["extra"] = header.get("extra") or {}
    snap["stop_state"] = stop_state  # None for v1 streams
    return snap
