"""Chat prompt templates.

* ``llama2``: the `[INST] <<SYS>>` schema the reference chat CLI renders
  (`/root/reference/src/apps/dllama/dllama.cpp:136-142`).
* ``llama3``: the header-id schema the reference API server renders
  (`/root/reference/src/apps/dllama-api/dllama-api.cpp:173-181`).
"""

from __future__ import annotations


def render_llama2_turn(user: str, system: str = "", first_turn: bool = False) -> str:
    if first_turn and system:
        return f"[INST] <<SYS>>\n{system}\n<</SYS>>\n\n{user} [/INST]"
    return f"[INST] {user} [/INST]"


def render_llama3_chat(messages: list) -> str:
    """messages: list of {"role": str, "content": str}. Appends the assistant header."""
    out = []
    for m in messages:
        out.append(f"<|start_header_id|>{m['role']}<|end_header_id|>\n\n{m['content']}<|eot_id|>")
    out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(out)


TEMPLATES = {"llama2": render_llama2_turn, "llama3": render_llama3_chat}
