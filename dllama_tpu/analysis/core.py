"""dllama-check core: findings, suppressions, file discovery, the runner.

Dependency-free by construction (``ast`` + stdlib only): the analyzer must be
runnable in the leanest CI job *before* jax is even importable, and must never
constrain what the runtime may import.

Suppression syntax (audited, reason mandatory; the rule id is spelled
``LOCK-nnn`` here so this docstring is not itself parsed as one)::

    self._hot = x  # dllama: allow[LOCK-nnn] reason=publish-only; readers tolerate tears

A suppression comment applies to findings on its own line or the line
directly below (comment-above style). One widening exists: a LOCK-nnn
allow on a ``def`` line whose reason starts with ``cross-module:`` covers
the whole method body (see ``analysis.callgraph`` — the interprocedural
proof is module-local, so externally-called helpers can never be proven).
A suppression with no ``reason=`` text
is itself a finding (SUP-001), and one whose rule no longer fires at that
site is a finding too (SUP-002, stale suppression) — the gate counts
unsuppressed findings only, so every exception to a rule stays visible in
the JSON report and dies when it stops being needed.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

_SUPPRESS_RE = re.compile(
    r"#\s*dllama:\s*allow\[([A-Z]+-\d+)\]\s*(?:reason=(.*))?$")


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""   # the allow-comment's reason when suppressed

    @property
    def id(self) -> str:
        """Stable finding id used in commit messages / reports."""
        return f"{self.rule}:{self.path}:{self.line}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["id"] = self.id
        return d

    def render(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclasses.dataclass
class Suppression:
    rule: str
    line: int
    reason: str


class SourceFile:
    """A parsed source file plus its suppression comments."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self.suppressions: list = []
        self.bad_suppressions: list = []  # Finding (SUP-001)
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rule, reason = m.group(1), (m.group(2) or "").strip()
            if not reason:
                self.bad_suppressions.append(Finding(
                    "SUP-001", self.rel, i,
                    f"allow[{rule}] without a reason= — suppressions must "
                    f"say why"))
                continue
            self.suppressions.append(Suppression(rule, i, reason))

    def suppression_for(self, rule: str, line: int):
        """A suppression on the finding's line, or the line above it."""
        for s in self.suppressions:
            if s.rule == rule and s.line in (line, line - 1):
                return s
        return None


@dataclasses.dataclass
class Report:
    findings: list
    files_scanned: int

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list:
        return [f for f in self.findings if f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.unsuppressed

    def to_json(self) -> str:
        counts: dict = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return json.dumps({
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "unsuppressed": [f.to_dict() for f in self.unsuppressed],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "counts_by_rule": counts,
        }, indent=2, sort_keys=True)

    def render(self) -> str:
        out = []
        for f in sorted(self.unsuppressed,
                        key=lambda f: (f.path, f.line, f.rule)):
            out.append(f.render())
        n_sup = len(self.suppressed)
        out.append(f"dllama-check: {len(self.unsuppressed)} finding(s), "
                   f"{n_sup} suppressed, {self.files_scanned} file(s)")
        return "\n".join(out)

    def to_sarif(self) -> str:
        """SARIF 2.1.0, so CI can annotate PR diffs with findings."""
        rules = sorted({f.rule for f in self.findings})
        results = []
        for f in sorted(self.findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            r = {
                "ruleId": f.rule,
                "level": "note" if f.suppressed else "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line},
                }}],
            }
            if f.suppressed:
                r["suppressions"] = [{"kind": "inSource",
                                      "justification": f.reason}]
            results.append(r)
        return json.dumps({
            "version": "2.1.0",
            "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
            "runs": [{
                "tool": {"driver": {
                    "name": "dllama-check",
                    "rules": [{"id": r} for r in rules],
                }},
                "results": results,
            }],
        }, indent=2, sort_keys=True)


def _apply_suppressions(findings: list, src: "SourceFile") -> list:
    for f in findings:
        s = src.suppression_for(f.rule, f.line)
        if s is not None:
            f.suppressed = True
            f.reason = s.reason
    return findings


def load_source(path: str, root: str) -> SourceFile:
    rel = os.path.relpath(path, root)
    with open(path, "r", encoding="utf-8") as fh:
        return SourceFile(path, rel, fh.read())


def discover(root: str) -> list:
    """Every .py under <root>/dllama_tpu, sorted for deterministic reports."""
    out = []
    pkg = os.path.join(root, "dllama_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def find_root(start: str | None = None) -> str:
    """The repo root: the directory holding the dllama_tpu package."""
    here = os.path.abspath(start or os.path.dirname(
        os.path.dirname(os.path.dirname(__file__))))
    if os.path.isdir(os.path.join(here, "dllama_tpu")):
        return here
    raise SystemExit(f"dllama-check: no dllama_tpu package under {here}")


def _stale_suppressions(sources, findings) -> list:
    """SUP-002: allow-comments whose rule no longer fires at that site.
    Interprocedural LOCK-001 made several suppressions obsolete; a stale
    allow silently hides the *next* real finding at that line."""
    out: list = []
    for src in sources:
        for s in src.suppressions:
            # A finding "hits" its suppression when anchored to the same
            # line (or the line below, comment-above style) — or, for
            # method-level cross-module LOCK-001 allows, when the finding
            # carries a suppressed_anchor pointing back at the comment.
            hit = any(f.rule == s.rule and f.path == src.rel
                      and (f.line in (s.line, s.line + 1)
                           or getattr(f, "suppressed_anchor", None) == s.line)
                      for f in findings)
            if not hit:
                out.append(Finding(
                    "SUP-002", src.rel, s.line,
                    f"stale suppression: allow[{s.rule}] but {s.rule} no "
                    f"longer fires here — delete the comment"))
    return out


def run(root: str | None = None, only_files=None) -> Report:
    """Run every pass over the tree rooted at ``root`` (default: the repo
    this package was imported from).  ``only_files`` (repo-relative paths)
    filters the *reported* findings for changed-files mode — every pass
    still sees the whole tree, so cross-file contracts stay sound."""
    from . import (blocking, callgraph, coverage, hygiene, locks,
                   pallas_tiling, protocol, tracesafety)
    root = find_root(root) if root is None else os.path.abspath(root)
    sources = []
    findings: list = []
    for path in discover(root):
        try:
            src = load_source(path, root)
        except SyntaxError as e:
            findings.append(Finding(
                "AST-001", os.path.relpath(path, root).replace(os.sep, "/"),
                e.lineno or 1, f"unparseable: {e.msg}"))
            continue
        sources.append(src)
        findings.extend(src.bad_suppressions)

    per_file_passes = (callgraph.check_guarded_writes,
                       locks.check_guarded_globals,
                       blocking.check_blocking,
                       tracesafety.check_trace_safety,
                       hygiene.check_exceptions,
                       pallas_tiling.check_blockspecs)
    for src in sources:
        for p in per_file_passes:
            findings.extend(_apply_suppressions(list(p(src)), src))

    # cross-file passes: suppressions still resolve against the file each
    # finding is anchored to
    by_rel = {s.rel: s for s in sources}
    for p in (locks.check_lock_order, locks.check_external_writes,
              protocol.check_protocol):
        for f in p(sources):
            src = by_rel.get(f.path)
            if src is not None:
                _apply_suppressions([f], src)
            findings.append(f)
    for f in coverage.check_fault_coverage(root, sources):
        src = by_rel.get(f.path)
        if src is not None:
            _apply_suppressions([f], src)
        findings.append(f)
    findings.extend(_stale_suppressions(sources, findings))
    if only_files:
        keep = {p.replace(os.sep, "/") for p in only_files}
        findings = [f for f in findings if f.path in keep]
    return Report(findings=findings, files_scanned=len(sources))


def analyze_source(text: str, filename: str = "snippet.py",
                   passes: tuple = ()) -> list:
    """Run per-file passes over a source string — the fixture-test entry.
    ``passes`` defaults to all per-file passes plus the cross-file lock
    passes applied to this single file."""
    from . import (blocking, callgraph, hygiene, locks, pallas_tiling,
                   tracesafety)
    src = SourceFile(filename, filename, text)
    findings: list = list(src.bad_suppressions)
    chosen = passes or (callgraph.check_guarded_writes,
                        locks.check_guarded_globals,
                        blocking.check_blocking,
                        tracesafety.check_trace_safety,
                        hygiene.check_exceptions,
                        pallas_tiling.check_blockspecs)
    for p in chosen:
        findings.extend(_apply_suppressions(list(p(src)), src))
    if not passes:
        for f in locks.check_lock_order([src]):
            _apply_suppressions([f], src)
            findings.append(f)
        for f in locks.check_external_writes([src]):
            _apply_suppressions([f], src)
            findings.append(f)
        findings.extend(_stale_suppressions([src], findings))
    return findings
