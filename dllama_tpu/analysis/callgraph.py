"""Interprocedural LOCK-001: prove "caller always holds X" across methods.

PR 7's LOCK-001 was deliberately lexical: a helper that writes a guarded
field while *its caller* holds the lock needed an allow-comment.  This
pass builds a per-class call graph (``self.<m>(...)`` sites with the
lexically-held lock set at each site) and exempts a guarded write when
the enclosing method is **provably** always entered with the lock held:

* the method is private (``_``-prefixed, non-dunder) or ``_locked``-
  suffixed — public methods are never proven, external callers are
  invisible to a module-level graph;
* it has at least one call site in the class; and
* EVERY call site either lexically holds ``with self.<lock>``, sits in
  ``__init__`` (construction is single-threaded), or is itself in a
  provable method (transitively, cycles count as unproven).

A genuinely unlocked call path is a finding whose message carries the
full chain, e.g. ``Gate.flush() -> Gate._bump_locked() called at
x.py:12``.  The conservative direction is preserved: this pass only ever
*removes* findings relative to the lexical rule, never adds sites.

Cross-module suppression: the proof is module-local by design, so a
helper whose only callers live in *another* module can never be proven
here.  Rather than forcing a per-write allow-comment on every such line,
a single method-level suppression on (or directly above) the ``def``
(the rule id is spelled ``LOCK-nnn`` here so this docstring is not
itself parsed as one)::

    def _publish(self):  # dllama: allow[LOCK-nnn] reason=cross-module:fleet.Controller._apply

suppresses every LOCK-001 inside that method, provided the reason names
the external callee (``cross-module:<dotted-callee>``).  Suppressed
findings carry ``suppressed_anchor`` (the ``def``-line of the allow) so
SUP-002 still audits the comment for staleness: when the method stops
producing LOCK-001 findings the anchor has nothing to suppress and the
comment is flagged stale like any other.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile
from .locks import _WithTracker, _writes_from_stmt, harvest_classes


def _eligible(name: str) -> bool:
    """Helpers we may try to prove; public methods are never provable."""
    if name.endswith("_locked"):
        return True
    return name.startswith("_") and not name.startswith("__")


class _Tracker(_WithTracker):
    """_WithTracker that also reports ``self.<m>(...)`` call sites."""

    def __init__(self, on_write, on_call, held0=()):
        super().__init__(on_write, held0)
        self.on_call = on_call

    def visit_Call(self, node: ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            self.on_call(f.attr, node.lineno, tuple(self.held))
        super().visit_Call(node)

    # nested defs run later with no lexically-held lock; their call sites
    # still count, but with an empty held set (conservative)
    def visit_FunctionDef(self, node):
        inner = _Tracker(self.on_write, self.on_call, held0=())
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def check_guarded_writes(src: SourceFile):
    """LOCK-001 over one file, with interprocedural lock proofs."""
    findings: list = []
    classes = harvest_classes(src)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guards = classes.get(node.name) or {}
        if not any(v is not None for v in guards.values()):
            continue

        methods = [m for m in node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
        writes: dict = {}       # method -> [(stmt, field, lock)] unlocked
        call_sites: dict = {}   # callee -> [(caller, lineno, held)]
        for meth in methods:
            bucket: list = []

            def on_write(stmt, held, _b=bucket):
                _writes_from_stmt(
                    stmt, held, guards,
                    lambda h, lock: h == f"self.{lock}",
                    lambda s, field, lock: _b.append((s, field, lock)))

            def on_call(callee, lineno, held, _m=meth.name):
                call_sites.setdefault(callee, []).append((_m, lineno, held))

            tracker = _Tracker(on_write, on_call)
            for stmt in meth.body:
                tracker.visit(stmt)
            writes[meth.name] = bucket

        def provable(meth_name, lock, stack):
            """Every call site of meth_name holds ``with self.<lock>``?"""
            if not _eligible(meth_name) or meth_name in stack:
                return False
            sites = call_sites.get(meth_name)
            if not sites:
                return False
            for caller, _lineno, held in sites:
                if f"self.{lock}" in held or caller == "__init__":
                    continue
                if not provable(caller, lock, stack | {meth_name}):
                    return False
            return True

        def unlocked_chain(meth_name, lock, stack):
            """One call path reaching meth_name lock-free, as display hops."""
            for caller, lineno, held in call_sites.get(meth_name) or ():
                if f"self.{lock}" in held or caller == "__init__":
                    continue
                if provable(caller, lock, stack | {meth_name}):
                    continue
                sub = None
                if caller not in stack:
                    sub = unlocked_chain(caller, lock, stack | {meth_name})
                head = sub or [f"{node.name}.{caller}()"]
                return head + [f"{node.name}.{meth_name}() called at "
                               f"{src.rel}:{lineno}"]
            return None

        for meth in methods:
            if meth.name == "__init__":
                continue
            xmod = _cross_module_suppression(src, meth)
            for stmt, field, lock in writes[meth.name]:
                if provable(meth.name, lock, frozenset()):
                    continue
                msg = (f"{node.name}.{field} written in {meth.name}() "
                       f"outside `with self.{lock}` (guarded_by({lock!r}))")
                if _eligible(meth.name):
                    chain = unlocked_chain(meth.name, lock, frozenset())
                    if chain:
                        msg += "; unlocked call path: " + " -> ".join(chain)
                    elif not call_sites.get(meth.name):
                        msg += ("; helper has no call site in this module — "
                                "cannot prove callers hold the lock")
                f = Finding("LOCK-001", src.rel, stmt.lineno, msg)
                if xmod is not None:
                    f.suppressed = True
                    f.reason = xmod.reason
                    # Anchors the finding to the def-line allow so SUP-002
                    # can still see this suppression doing work.
                    f.suppressed_anchor = xmod.line
                findings.append(f)
    return findings


def _cross_module_suppression(src: SourceFile, meth):
    """A method-level ``allow[LOCK-001] reason=cross-module:<callee>`` on
    (or directly above) the ``def`` line — the only suppression shape that
    may cover a whole method body, because a module-local graph cannot see
    the external caller that holds the lock."""
    for s in src.suppressions:
        if (s.rule == "LOCK-001" and s.line in (meth.lineno, meth.lineno - 1)
                and s.reason.startswith("cross-module:")):
            return s
    return None
