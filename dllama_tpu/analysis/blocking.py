"""BLOCK-00x / LOOP-001: blocking operations where a block stalls others.

BLOCK-001  blocking call lexically inside ``with self.<lock>`` where
           <lock> is a guard lock declared by the enclosing class's
           ``@guarded_by`` decorators — the classic router/api_server
           latency-collapse shape (every other thread that touches the
           guarded state stalls behind one slow socket).
BLOCK-002  blocking call while holding a module-level lock (declared via
           ``guard_globals`` or bound to ``threading.Lock()``/``RLock()``
           at module scope).
LOOP-001   blocking call ANYWHERE inside a function annotated
           ``@loop_callback`` (``analysis.sanitize``) — event-loop
           callbacks/coroutines run on the single ``selectors`` loop
           thread (``serving/evloop.py``), where one blocking call
           stalls EVERY connection the process carries; no lock needs
           to be held for the collapse. Nested ``def``s inherit the
           annotation (they run on the same thread). The loop's audited
           non-blocking leaf primitives stay UNannotated on purpose:
           they are the few lines allowed to touch raw socket calls.

"Blocking" is a deliberate shortlist, not a taint analysis:

* ``time.sleep`` / any dotted ``.sleep(...)``
* ``subprocess.run/Popen/call/check_call/check_output``
* socket I/O: ``.connect/.recv/.recvfrom/.recv_into/.accept/.sendall``
  and ``socket.create_connection``
* HTTP: ``.getresponse()``, ``urlopen(...)``, and ``.request(...)`` on a
  receiver whose name mentions ``conn``
* no-timeout queue/thread waits: zero-argument ``.get()`` (dict.get
  always takes an argument) unless ``block=False``/``timeout=`` given,
  and zero-argument ``.join()``
* ``select.select(...)``

``Condition.wait`` is deliberately NOT listed: waiting on a condition
*releases* its lock — flagging it would punish the one blocking call
that is correct under a lock.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile
from .locks import (_WithTracker, _dotted, harvest_classes,
                    harvest_global_guards, _module_level_locks)

_SUBPROCESS = frozenset({"run", "Popen", "call", "check_call", "check_output"})
_SOCKET = frozenset({"connect", "recv", "recvfrom", "recv_into", "accept",
                     "sendall", "create_connection"})


def _kw(call: ast.Call, name: str):
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def blocking_reason(call: ast.Call):
    """Short human label when ``call`` is on the blocking shortlist."""
    dotted = _dotted(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    leaf = parts[-1]
    recv = ".".join(parts[:-1])
    if leaf == "sleep":
        return f"{dotted}()"
    if recv == "subprocess" and leaf in _SUBPROCESS:
        return f"{dotted}()"
    if leaf in _SOCKET:
        return f"socket I/O via .{leaf}()"
    if leaf in ("getresponse", "urlopen"):
        return f"HTTP I/O via {dotted}()"
    if leaf == "request" and "conn" in recv.lower():
        return f"HTTP I/O via {dotted}()"
    if dotted == "select.select":
        return "select.select()"
    if leaf == "get" and recv and not call.args:
        block = _kw(call, "block")
        if (isinstance(block, ast.Constant) and block.value is False):
            return None
        if _kw(call, "timeout") is None:
            return f"no-timeout {recv}.get()"
    if leaf == "join" and recv and not call.args and not call.keywords:
        return f"no-timeout {recv}.join()"
    return None


class _BlockTracker(_WithTracker):
    """_WithTracker that reports blocking calls with the held lock set."""

    def __init__(self, on_block, held0=()):
        super().__init__(lambda *_: None, held0)
        self.on_block = on_block

    def visit_Call(self, node: ast.Call):
        reason = blocking_reason(node)
        if reason is not None:
            self.on_block(node, reason, list(self.held))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        inner = _BlockTracker(self.on_block, held0=())
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef


def check_blocking(src: SourceFile):
    """BLOCK-001/002 over one file."""
    findings: list = []
    classes = harvest_classes(src)
    module_locks = set(_module_level_locks(src))
    module_locks.update(harvest_global_guards(src).values())

    # BLOCK-001: methods of guard-annotated classes
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guards = classes.get(node.name) or {}
        guard_locks = {v for v in guards.values() if v}
        if not guard_locks:
            continue
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue

            def on_block(call, reason, held, _m=meth):
                for lock in sorted(guard_locks):
                    if f"self.{lock}" in held:
                        findings.append(Finding(
                            "BLOCK-001", src.rel, call.lineno,
                            f"blocking {reason} in {node.name}.{_m.name}() "
                            f"while holding self.{lock} (guarded_by) — move "
                            f"the I/O outside the lock or snapshot state "
                            f"first"))
                        return

            tracker = _BlockTracker(on_block)
            for stmt in meth.body:
                tracker.visit(stmt)

    # BLOCK-002: any function holding a module-level lock
    if module_locks:
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue

            def on_block(call, reason, held, _f=node):
                hot = sorted(set(held) & module_locks)
                if hot:
                    findings.append(Finding(
                        "BLOCK-002", src.rel, call.lineno,
                        f"blocking {reason} in {_f.name}() while holding "
                        f"module lock {hot[0]} — move the I/O outside the "
                        f"lock"))

            tracker = _BlockTracker(on_block)
            for stmt in node.body:
                tracker.visit(stmt)

    # LOOP-001: blocking calls inside @loop_callback functions — no lock
    # required; the loop thread IS the contended resource. The dedupe set
    # is file-wide: a nested def that is itself annotated must not report
    # the same call twice (once per enclosing walk).
    seen: set = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_loop_callback(node):
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            reason = blocking_reason(call)
            if reason is None:
                continue
            key = (call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "LOOP-001", src.rel, call.lineno,
                f"blocking {reason} in event-loop callback {node.name}() — "
                f"one blocking call on the loop thread stalls every "
                f"connection; yield to the loop (evloop primitives) or "
                f"ship it to a worker via evloop.run_in_thread"))
    return findings


def _is_loop_callback(fn) -> bool:
    """Does ``fn`` carry the ``@loop_callback`` annotation (bare or
    dotted, optionally called)?"""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted(target)
        if dotted and dotted.split(".")[-1] == "loop_callback":
            return True
    return False
