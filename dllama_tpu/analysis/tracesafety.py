"""JAX trace-safety lints.

TRACE-001  Python ``if``/``while`` (or conditional expression) branching on a
           traced value inside a jit/shard_map region — under tracing the
           condition is an abstract value; ``bool()`` on it either raises a
           ConcretizationTypeError or silently bakes one branch into the
           compiled program.
TRACE-002  host pulls on traced values inside a jit region: ``.item()`` /
           ``.tolist()``, ``float()/int()/bool()``, or ``np.*`` calls — each
           forces a device sync (or a tracer leak) inside the traced
           function.
TRACE-003  mutation of Python state captured by a jitted closure
           (``nonlocal``/``global`` rebinding, in-place mutator calls or
           item-writes on free variables) — jit replays the traced function
           zero or many times, so captured-state mutation desynchronizes
           from execution.

Region discovery: functions decorated with ``jax.jit`` / ``jit`` /
``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)`` /
``shard_map`` variants, plus defs and lambdas passed directly to
``jax.jit(...)`` / ``shard_map(...)`` call sites in the same scope.

Taint model (deliberately precision-first): non-static parameters of a jit
region are roots; taint flows through arithmetic/comparison, subscripting,
tuple packing/unpacking and calls on or of tainted values; it STOPS at
attribute access (``x.shape``/``x.ndim``/``cfg.flag`` are static under
trace), ``len()``/``isinstance()``/``type()``/``range()``, and ``is``/``is
not`` comparisons (identity on tracers is legal Python). ``static_argnames``
/ ``static_argnums`` remove parameters from the root set.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

_JIT_NAMES = {"jit"}
_SHARD_NAMES = {"shard_map"}
_SAFE_CALLS = {"len", "isinstance", "type", "range", "enumerate", "getattr",
               "hasattr", "zip", "print", "id", "repr", "str"}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_HOST_METHODS = {"item", "tolist"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "add", "discard", "update", "setdefault", "appendleft"}


def _leaf_name(func) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _jit_call_info(call: ast.Call):
    """(kind, statics) when ``call`` is jax.jit(...)/shard_map(...)/
    partial(jax.jit, ...); kind None otherwise. statics = (names, nums)."""
    leaf = _leaf_name(call.func)
    if leaf in _JIT_NAMES or leaf in _SHARD_NAMES:
        return ("shard" if leaf in _SHARD_NAMES else "jit",
                _static_args(call))
    if leaf == "partial" and call.args:
        inner = call.args[0]
        inner_leaf = _leaf_name(inner) if isinstance(
            inner, (ast.Attribute, ast.Name)) else ""
        if inner_leaf in _JIT_NAMES | _SHARD_NAMES:
            return ("shard" if inner_leaf in _SHARD_NAMES else "jit",
                    _static_args(call))
    return (None, None)


def _static_args(call: ast.Call):
    names: set = set()
    nums: set = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


def find_jit_regions(src: SourceFile):
    """[(func_node, static_names, static_nums)] for every traced region."""
    regions: list = []
    seen: set = set()

    def add(fn, statics):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        names, nums = statics if statics else (set(), set())
        regions.append((fn, names, nums))

    # defs by scope, to resolve jax.jit(fn_name) references
    defs_by_name: dict = {}
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)

    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    kind, statics = _jit_call_info(dec)
                    if kind:
                        add(node, statics)
                elif _leaf_name(dec) in _JIT_NAMES | _SHARD_NAMES:
                    add(node, (set(), set()))
        elif isinstance(node, ast.Call):
            kind, statics = _jit_call_info(node)
            if not kind:
                continue
            # jax.jit(lambda...) / jax.jit(local_fn) / shard_map(f, mesh...)
            for arg in node.args[:1] if _leaf_name(node.func) != "partial" \
                    else node.args[1:2]:
                if isinstance(arg, ast.Lambda):
                    add(arg, statics)
                elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                    add(defs_by_name[arg.id], statics)
    return regions


def _params(fn):
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]


class _Taint:
    """Two-pass lexical taint over one traced function."""

    def __init__(self, tainted0: set):
        self.tainted = set(tainted0)

    def expr(self, node) -> bool:
        t = self.tainted
        if isinstance(node, ast.Name):
            return node.id in t
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity on tracers is fine
            return self.expr(node.left) or any(
                self.expr(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return (self.expr(node.test) or self.expr(node.body)
                    or self.expr(node.orelse))
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Attribute):
            return False  # .shape/.ndim/.dtype/cfg.flag: static under trace
        if isinstance(node, ast.Call):
            leaf = _leaf_name(node.func)
            if leaf in _SAFE_CALLS:
                return False
            if leaf in _HOST_METHODS:
                return False  # already a TRACE-002; result is host-side
            recv_tainted = (isinstance(node.func, ast.Attribute)
                            and self.expr(node.func.value))
            args_tainted = any(self.expr(a) for a in node.args) or any(
                self.expr(kw.value) for kw in node.keywords)
            return recv_tainted or args_tainted
        if isinstance(node, ast.Lambda):
            return False
        return False

    def assign(self, stmt):
        if isinstance(stmt, ast.Assign):
            tainted = self.expr(stmt.value)
            for t in stmt.targets:
                self._mark(t, tainted)
        elif isinstance(stmt, ast.AugAssign):
            if self.expr(stmt.value) or self.expr(stmt.target):
                self._mark(stmt.target, True)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._mark(stmt.target, self.expr(stmt.value))

    def _mark(self, target, tainted: bool):
        for leaf in _unpack(target):
            if isinstance(leaf, ast.Name):
                if tainted:
                    self.tainted.add(leaf.id)
                else:
                    self.tainted.discard(leaf.id)


def _unpack(t):
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _unpack(e)
    elif isinstance(t, ast.Starred):
        yield from _unpack(t.value)
    else:
        yield t


def _np_root(func) -> bool:
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in ("np", "numpy")


def check_trace_safety(src: SourceFile):
    findings: list = []
    for fn, static_names, static_nums in find_jit_regions(src):
        findings.extend(_check_region(src, fn, static_names, static_nums))
    return findings


def _check_region(src: SourceFile, fn, static_names, static_nums):
    findings: list = []
    params = _params(fn)
    roots = {p for i, p in enumerate(params)
             if p not in static_names and i not in static_nums}
    roots.discard("self")

    # locals of this region (for TRACE-003 free-variable detection)
    local_names = set(params)
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                for leaf in _unpack(t):
                    if isinstance(leaf, ast.Name):
                        local_names.add(leaf.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            for leaf in _unpack(node.target):
                if isinstance(leaf, ast.Name):
                    local_names.add(leaf.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for leaf in _unpack(node.target):
                if isinstance(leaf, ast.Name):
                    local_names.add(leaf.id)
        elif isinstance(node, ast.comprehension):
            for leaf in _unpack(node.target):
                if isinstance(leaf, ast.Name):
                    local_names.add(leaf.id)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for leaf in _unpack(node.optional_vars):
                if isinstance(leaf, ast.Name):
                    local_names.add(leaf.id)
    declared_free = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Nonlocal, ast.Global)):
            declared_free.update(node.names)

    taint = _Taint(roots)
    # nested defs run under the same trace: their params are traced values
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            for p in _params(node):
                taint.tainted.add(p)

    def scan_once(emit: bool):
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                taint.assign(node)
                if emit and isinstance(node, (ast.Assign, ast.AugAssign)):
                    _trace3_item_write(node)
            elif isinstance(node, (ast.If, ast.While)):
                if emit and taint.expr(node.test):
                    findings.append(Finding(
                        "TRACE-001", src.rel, node.lineno,
                        f"`{'while' if isinstance(node, ast.While) else 'if'}`"
                        f" on a traced value inside a jit region "
                        f"({_region_name(fn)}) — use jnp.where/lax.cond"))
            elif isinstance(node, ast.IfExp):
                if emit and taint.expr(node.test):
                    findings.append(Finding(
                        "TRACE-001", src.rel, node.lineno,
                        f"conditional expression on a traced value inside a "
                        f"jit region ({_region_name(fn)}) — use jnp.where"))
            elif isinstance(node, ast.Call):
                if emit:
                    _trace2(node)
                    _trace3_call(node)

    def _trace2(call: ast.Call):
        leaf = _leaf_name(call.func)
        if (leaf in _HOST_METHODS and isinstance(call.func, ast.Attribute)
                and taint.expr(call.func.value)):
            findings.append(Finding(
                "TRACE-002", src.rel, call.lineno,
                f".{leaf}() on a traced value inside a jit region "
                f"({_region_name(fn)}) — host pull under trace"))
        elif (isinstance(call.func, ast.Name) and leaf in _HOST_CASTS
                and any(taint.expr(a) for a in call.args)):
            findings.append(Finding(
                "TRACE-002", src.rel, call.lineno,
                f"{leaf}() on a traced value inside a jit region "
                f"({_region_name(fn)}) — concretizes the tracer"))
        elif (_np_root(call.func)
                and (any(taint.expr(a) for a in call.args)
                     or any(taint.expr(kw.value) for kw in call.keywords))):
            findings.append(Finding(
                "TRACE-002", src.rel, call.lineno,
                f"np.{_leaf_name(call.func)}() on a traced value inside a "
                f"jit region ({_region_name(fn)}) — use jnp"))

    def _trace3_call(call: ast.Call):
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr in _MUTATORS):
            return
        recv = call.func.value
        if (isinstance(recv, ast.Name)
                and (recv.id in declared_free
                     or recv.id not in local_names)):
            findings.append(Finding(
                "TRACE-003", src.rel, call.lineno,
                f"in-place .{call.func.attr}() on captured variable "
                f"`{recv.id}` inside a jit region ({_region_name(fn)}) — "
                f"jit replays the trace; captured-state mutation "
                f"desynchronizes"))

    def _trace3_item_write(stmt):
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for t in targets:
            for leaf in _unpack(t):
                if isinstance(leaf, ast.Name) and leaf.id in declared_free:
                    findings.append(Finding(
                        "TRACE-003", src.rel, stmt.lineno,
                        f"rebinding captured variable `{leaf.id}` "
                        f"(nonlocal/global) inside a jit region "
                        f"({_region_name(fn)})"))
                elif isinstance(leaf, ast.Subscript):
                    base = leaf.value
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if (isinstance(base, ast.Name)
                            and base.id not in local_names):
                        findings.append(Finding(
                            "TRACE-003", src.rel, stmt.lineno,
                            f"item-write into captured variable "
                            f"`{base.id}` inside a jit region "
                            f"({_region_name(fn)})"))

    scan_once(emit=False)   # settle taint through forward references/loops
    scan_once(emit=True)
    return findings


def _region_name(fn) -> str:
    return getattr(fn, "name", None) or f"<lambda>:{fn.lineno}"
