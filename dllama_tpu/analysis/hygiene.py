"""Exception-hygiene pass.

EXC-001  bare ``except:`` — catches SystemExit/KeyboardInterrupt and hides
         the injected-fault paths the chaos suite depends on; name the
         exception (``except Exception:`` at minimum).
EXC-002  silently swallowed exception: a handler whose entire body is
         ``pass``/``continue`` with no comment anywhere on the handler —
         deliberate swallows are fine, but they must say why (a comment on
         the ``except`` or body line satisfies the rule).
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile


def _has_comment(src: SourceFile, start: int, end: int) -> bool:
    for ln in range(start, min(end, len(src.lines)) + 1):
        if "#" in src.lines[ln - 1]:
            return True
    return False


def check_exceptions(src: SourceFile):
    findings: list = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                "EXC-001", src.rel, node.lineno,
                "bare `except:` — catches SystemExit/KeyboardInterrupt; "
                "name the exception"))
            continue
        body_is_swallow = all(
            isinstance(s, (ast.Pass, ast.Continue)) for s in node.body)
        if body_is_swallow:
            end = max(getattr(s, "lineno", node.lineno) for s in node.body)
            if not _has_comment(src, node.lineno, end):
                findings.append(Finding(
                    "EXC-002", src.rel, node.lineno,
                    "exception swallowed with no explanation — add a "
                    "comment saying why ignoring is safe"))
    return findings
