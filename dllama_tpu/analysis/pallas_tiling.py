"""Pallas BlockSpec tiling lint.

PALLAS-001  a ``pl.BlockSpec`` whose block-shape tuple has an int LITERAL
            in either of its last two positions that is not divisible by
            the Mosaic minimum tile — 8 for the sublane (second-to-last)
            dim, 128 for the lane (last) dim.

Why literals only: symbolic dims (``bk``, ``hd // 2``) come from the tile
planner, whose outputs the CPU lowering gate (ops.lowering) sweeps against
every real model shape — a misalignment there fails tests, not this lint.
A misaligned *literal*, by contrast, is exactly how the BENCH_r02 failure
shipped: it looks innocent at the call site, lowers nowhere, and no test
exercises it until a TPU does. Mosaic does accept such a block when it
spans the whole array dim ("equal-to-dim" escape), but whether it does is
a runtime fact this pass cannot see — so a deliberate whole-array literal
must carry (rule id spelled out here so this docstring is not itself
parsed as a suppression)::

    # dllama: allow[PALLAS-nnn] reason=whole-array dim (proven: tests/test_lowering.py sweep)

which keeps every exception audited (SUP-001) and auto-expiring (SUP-002)
and, per the reason convention above, pointing at the sweep case that
proves it.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

_SUBLANE, _LANE = 8, 128


def _block_shape(call: ast.Call):
    """The block-shape tuple of a BlockSpec call, or None.

    Accepts the positional form ``BlockSpec((..), index_map)`` and the
    keyword form ``BlockSpec(block_shape=(..))``; memory-space-only specs
    (``BlockSpec(memory_space=pl.ANY)``) have no shape to check.
    """
    if call.args and isinstance(call.args[0], ast.Tuple):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "block_shape" and isinstance(kw.value, ast.Tuple):
            return kw.value
    return None


def check_blockspecs(src: SourceFile):
    findings = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if name != "BlockSpec":
            continue
        shape = _block_shape(node)
        if shape is None or not shape.elts:
            continue
        # (dim, minimum, axis-name) for the last two positions; a 1-D
        # block only has a lane dim
        tail = [(shape.elts[-1], _LANE, "lane")]
        if len(shape.elts) >= 2:
            tail.append((shape.elts[-2], _SUBLANE, "sublane"))
        for elt, mult, axis in tail:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)
                    and not isinstance(elt.value, bool)):
                continue  # planner-derived symbolic dim: the sweep's job
            if elt.value % mult == 0:
                continue
            findings.append(Finding(
                "PALLAS-001", src.rel, elt.lineno,
                f"literal {axis} block dim {elt.value} is not divisible by "
                f"{mult} — lowers under Mosaic only if it equals the array "
                f"dim; if so, suppress with a reason naming the sweep case "
                f"that proves it"))
    return findings
