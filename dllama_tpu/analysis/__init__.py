"""dllama-check: dependency-free static analysis + runtime sanitizer.

Static half (``python -m dllama_tpu.analysis``): AST passes proving lock
discipline (LOCK-*), JAX trace-safety (TRACE-*), fault-site coverage
(FAULT-*) and exception hygiene (EXC-*) over the whole package — zero
unsuppressed findings is a CI gate. Runtime half (:mod:`.sanitize`): the
``guarded_by`` annotation convention plus a ``DLLAMA_SANITIZE=1`` lock
witness that catches order inversions and unguarded writes live.

This ``__init__`` stays import-light: the serving/runtime modules import
``analysis.sanitize`` on their hot import path, so the AST machinery loads
only when the analyzer actually runs.
"""

from __future__ import annotations

__all__ = ["run", "analyze_source", "Finding", "Report"]


def __getattr__(name):
    if name in __all__:
        from . import core
        return getattr(core, name)
    raise AttributeError(name)
