"""Fault-site coverage cross-checks — the PR-6 README drift, made impossible.

The single source of truth is ``dllama_tpu/faults.py``: its ``SITES`` tuple
(registration order) and ``SITE_METRICS`` map (site -> the metric family that
proves the site's failure is *visible*). Everything else is derived:

FAULT-001  a ``faults.fire("<site>")`` call names a site missing from
           ``SITES`` (it would silently never fire — ``fire()`` does not
           validate), or a registered site is never fired anywhere in the
           package (dead registration).
FAULT-002  the README's ``# sites:`` block is not byte-identical to the
           block generated from ``SITES`` (``python -m dllama_tpu.analysis
           --print-fault-sites`` emits the canonical block to paste).
FAULT-003  a site has no ``SITE_METRICS`` entry, or its metric name is not
           registered anywhere in the package — a fault you cannot see on
           /metrics is a fault the obs drill cannot prove.
FAULT-004  a site is not exercised by any test under tests/ (the string
           never appears in a test file).
"""

from __future__ import annotations

import ast
import os
import re

from .core import Finding, SourceFile

_WIDTH = 66


def render_site_block(sites) -> str:
    """The canonical README site list, generated from ``SITES``."""
    lines: list = []
    cur = "# sites: "
    for i, s in enumerate(sites):
        piece = s if i == 0 else f" | {s}"
        if len(cur) + len(piece) > _WIDTH and cur.strip() != "# sites:":
            lines.append(cur)
            cur = "#        " + f"| {s}"
        else:
            cur += piece
    lines.append(cur)
    return "\n".join(lines)


def _faults_registry(root: str):
    """(sites, site_metrics, sites_line, metrics_line) parsed from the AST
    of dllama_tpu/faults.py — no import, so the analyzer never executes the
    code it checks."""
    path = os.path.join(root, "dllama_tpu", "faults.py")
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    sites: tuple = ()
    metrics: dict = {}
    sites_line = metrics_line = 1
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "SITES" and isinstance(node.value, (ast.Tuple,
                                                           ast.List)):
                sites = tuple(e.value for e in node.value.elts
                              if isinstance(e, ast.Constant))
                sites_line = node.lineno
            elif t.id == "SITE_METRICS" and isinstance(node.value, ast.Dict):
                metrics_line = node.lineno
                for k, v in zip(node.value.keys, node.value.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(v, ast.Constant)):
                        metrics[k.value] = v.value
    return sites, metrics, sites_line, metrics_line


def _fired_sites(sources):
    """{site: [(rel, line)]} for every faults.fire("<lit>") call."""
    out: dict = {}
    for src in sources:
        if src.rel.endswith("analysis/coverage.py"):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fnname = (node.func.attr if isinstance(node.func, ast.Attribute)
                      else node.func.id if isinstance(node.func, ast.Name)
                      else "")
            if fnname != "fire":
                continue
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                out.setdefault(a.value, []).append((src.rel, node.lineno))
    return out


def check_fault_coverage(root: str, sources):
    findings: list = []
    rel_faults = "dllama_tpu/faults.py"
    try:
        sites, site_metrics, sites_line, metrics_line = _faults_registry(root)
    except OSError:
        return [Finding("FAULT-001", rel_faults, 1,
                        "dllama_tpu/faults.py unreadable")]
    fired = _fired_sites(sources)

    # FAULT-001 — both directions
    for site, locs in sorted(fired.items()):
        if site not in sites:
            rel, line = locs[0]
            findings.append(Finding(
                "FAULT-001", rel, line,
                f"faults.fire({site!r}) names an unregistered site — it "
                f"will silently never fire (SITES: {', '.join(sites)})"))
    for site in sites:
        if site not in fired:
            findings.append(Finding(
                "FAULT-001", rel_faults, sites_line,
                f"site {site!r} is registered but never fired anywhere in "
                f"dllama_tpu/ — dead registration"))

    # FAULT-002 — README block must be exactly the generated one
    readme = os.path.join(root, "README.md")
    block = render_site_block(sites)
    try:
        with open(readme, "r", encoding="utf-8") as fh:
            readme_text = fh.read()
    except OSError:
        readme_text = ""
    if block not in readme_text:
        findings.append(Finding(
            "FAULT-002", rel_faults, sites_line,
            "README.md fault-site list is stale: it must contain the block "
            "generated from faults.SITES — run `python -m "
            "dllama_tpu.analysis --print-fault-sites` and paste it"))

    # FAULT-003 — metric seam per site
    pkg_text = "\n".join(s.text for s in sources
                         if s.rel != rel_faults)
    for site in sites:
        metric = site_metrics.get(site)
        if not metric:
            findings.append(Finding(
                "FAULT-003", rel_faults, metrics_line,
                f"site {site!r} has no SITE_METRICS entry — every fault "
                f"site needs a metric seam proving its failure is visible"))
        elif f'"{metric}"' not in pkg_text:
            findings.append(Finding(
                "FAULT-003", rel_faults, metrics_line,
                f"SITE_METRICS[{site!r}] = {metric!r} is not registered "
                f"anywhere in dllama_tpu/"))
    for site in site_metrics:
        if site not in sites:
            findings.append(Finding(
                "FAULT-003", rel_faults, metrics_line,
                f"SITE_METRICS names unknown site {site!r}"))

    # FAULT-004 — every site exercised by at least one test
    tests_dir = os.path.join(root, "tests")
    test_text = []
    if os.path.isdir(tests_dir):
        for fn in sorted(os.listdir(tests_dir)):
            if fn.endswith(".py"):
                with open(os.path.join(tests_dir, fn), "r",
                          encoding="utf-8") as fh:
                    test_text.append(fh.read())
    test_blob = "\n".join(test_text)
    for site in sites:
        if not re.search(rf"\b{re.escape(site)}\b", test_blob):
            findings.append(Finding(
                "FAULT-004", rel_faults, sites_line,
                f"site {site!r} is not exercised by any test under tests/"))
    return findings
