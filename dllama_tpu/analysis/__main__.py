"""CLI: ``python -m dllama_tpu.analysis [--json] [--sarif PATH] [--only RULE]
[--files F ...] [--budget-s N] [--root DIR]``.

Exit 0 when the tree has zero unsuppressed findings (after ``--only`` /
``--files`` filtering) AND the run beat ``--budget-s``, 1 otherwise — the
``dllama-check`` CI job is exactly this command.
"""

from __future__ import annotations

import argparse
import sys
import time

from . import core


def _rule_match(rule: str, selectors) -> bool:
    """``--only LOCK-001`` matches exactly; ``--only PROTO`` matches the
    whole family."""
    for sel in selectors:
        if rule == sel or rule.startswith(sel.rstrip("-") + "-"):
            return True
    return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dllama_tpu.analysis",
        description="dllama-check: lock discipline (interprocedural), "
                    "blocking-under-lock, wire-protocol conformance, JAX "
                    "trace-safety, fault-site coverage and exception "
                    "hygiene.")
    ap.add_argument("--json", action="store_true",
                    help="emit the full machine-readable report")
    ap.add_argument("--sarif", metavar="PATH", default=None,
                    help="also write a SARIF 2.1.0 report to PATH "
                         "(CI uses it to annotate PR diffs)")
    ap.add_argument("--only", metavar="RULE", action="append", default=[],
                    help="report only this rule id (LOCK-001) or family "
                         "(PROTO); repeatable")
    ap.add_argument("--files", metavar="F", nargs="+", default=None,
                    help="changed-files mode: analyze the whole tree (cross-"
                         "file contracts need it) but report findings only "
                         "in these repo-relative paths")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="fail (exit 1) when the run takes longer than this "
                         "many seconds, even with zero findings")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree this package "
                         "was imported from)")
    ap.add_argument("--print-fault-sites", action="store_true",
                    help="print the canonical README site block generated "
                         "from faults.SITES, then exit")
    args = ap.parse_args(argv)

    if args.print_fault_sites:
        from . import coverage
        root = core.find_root(args.root)
        sites, _, _, _ = coverage._faults_registry(root)
        print(coverage.render_site_block(sites))
        return 0

    t0 = time.perf_counter()
    report = core.run(args.root, only_files=args.files)
    elapsed = time.perf_counter() - t0
    if args.only:
        report = core.Report(
            findings=[f for f in report.findings
                      if _rule_match(f.rule, args.only)],
            files_scanned=report.files_scanned)
    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            fh.write(report.to_sarif())
    if args.json:
        print(report.to_json())
    else:
        print(report.render())
    if args.budget_s is not None and elapsed > args.budget_s:
        print(f"dllama-check: runtime budget exceeded: {elapsed:.1f}s > "
              f"{args.budget_s:.1f}s", file=sys.stderr)
        return 1
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
