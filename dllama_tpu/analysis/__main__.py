"""CLI: ``python -m dllama_tpu.analysis [--json] [--root DIR]``.

Exit 0 when the tree has zero unsuppressed findings, 1 otherwise — the
``dllama-check`` CI job is exactly this command.
"""

from __future__ import annotations

import argparse
import sys

from . import core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dllama_tpu.analysis",
        description="dllama-check: lock discipline, JAX trace-safety, "
                    "fault-site coverage and exception hygiene.")
    ap.add_argument("--json", action="store_true",
                    help="emit the full machine-readable report")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree this package "
                         "was imported from)")
    ap.add_argument("--print-fault-sites", action="store_true",
                    help="print the canonical README site block generated "
                         "from faults.SITES, then exit")
    args = ap.parse_args(argv)

    if args.print_fault_sites:
        from . import coverage
        root = core.find_root(args.root)
        sites, _, _, _ = coverage._faults_registry(root)
        print(coverage.render_site_block(sites))
        return 0

    report = core.run(args.root)
    if args.json:
        print(report.to_json())
    else:
        print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
