"""Runtime concurrency sanitizer + the ``guarded_by`` annotation convention.

Two consumers share the annotations declared here:

* the **static analyzer** (``python -m dllama_tpu.analysis``) reads the
  ``@guarded_by(...)`` / ``guard_globals(...)`` calls from the AST and proves
  every write to an annotated attribute happens under ``with self.<lock>`` —
  lexically or via an always-called-under-lock helper (rule LOCK-001 and
  friends, interprocedural since dllama-check v2);
* the **runtime sanitizer**, enabled by ``DLLAMA_SANITIZE=1``, instruments the
  annotated classes at import time: each declared lock is replaced by a
  :class:`LockWitness` that records per-thread acquisition order into a global
  lock-order graph (cycle => :class:`LockOrderError`), ``__setattr__`` is
  wrapped to verify the declared lock is held whenever a guarded field is
  rebound (:class:`UnguardedWriteError`), and classes annotated with
  :func:`check_invariants` auto-run their invariant oracle after every
  mutating op (how ``PageAllocator.check()`` runs after every alloc/ref/unref
  in the sanitized CI lane).

When ``DLLAMA_SANITIZE`` is unset the decorators only attach metadata
(``__guarded_fields__`` / ``__invariant_check__``) and return the class
object unchanged — no wrapper enters the import path, no per-call overhead
exists (tests/test_analysis.py asserts the lock is a plain ``_thread.lock``
and ``__init__``/``__setattr__`` are untouched).

Known limits, by design:

* only **writes** (attribute rebinding) are checked at runtime; in-place
  container mutation (``self._rows[k] = v``) bypasses ``__setattr__`` and is
  covered by the static pass instead;
* a ``threading.Condition`` built in ``__init__`` on a declared lock is
  retargeted to the witness after instrumentation, and the witness supplies
  ``_release_save``/``_acquire_restore`` — so ownership bookkeeping is
  **exact** across ``Condition.wait`` (the witness releases and reacquires
  with the condition; a guarded write right after ``wait()`` is correctly
  seen as guarded).  A condition constructed *after* ``__init__``, or on a
  lock not declared via ``guarded_by``, stays raw and is best-effort;
* lock-order nodes are keyed ``ClassName.<attr>`` across classes and
  ``ClassName.<attr>#<instance-serial>`` within a class, so two instances
  of the same class acquired in opposite orders IS a reported inversion;
  re-entrant re-acquisition of a witness already on the thread's stack is
  excluded by identity, never by name.
"""

from __future__ import annotations

import functools
import itertools
import os
import threading

ENV_VAR = "DLLAMA_SANITIZE"


def enabled() -> bool:
    """Live read of the env switch (the module freezes it at import)."""
    return os.environ.get(ENV_VAR, "") not in ("", "0")


#: frozen at import time: annotated classes are instrumented at class-creation
#: (decoration) time, so flipping the env var after import has no effect.
#: Tests monkeypatch this before defining fixture classes.
_ENABLED = enabled()


class SanitizerError(AssertionError):
    """Base for sanitizer reports. An AssertionError subclass so the chaos
    suites fail loudly under ``DLLAMA_SANITIZE=1`` without new plumbing."""


class LockOrderError(SanitizerError):
    """Two locks were acquired in both orders somewhere in the process."""


class UnguardedWriteError(SanitizerError):
    """A ``guarded_by``-annotated field was rebound without its lock held."""


# ---------------------------------------------------------------------------
# lock-order graph (global, process-wide)
# ---------------------------------------------------------------------------

_order_lock = threading.Lock()
#: directed edges: lock name held -> lock name acquired while held
_order_edges: dict = {}
#: (src, dst) -> first-seen stack hint (kept tiny: just thread name)
_tls = threading.local()


def reset_order_graph() -> None:
    """Drop all recorded acquisition edges (test isolation)."""
    with _order_lock:
        _order_edges.clear()


def order_edges() -> dict:
    """Snapshot of the acquisition graph {src: set(dst)} (introspection)."""
    with _order_lock:
        return {k: set(v) for k, v in _order_edges.items()}


def _find_path(graph: dict, start: str, goal: str) -> list | None:
    """DFS path start -> goal through ``graph`` (caller holds _order_lock)."""
    stack = [(start, [start])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == goal:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in graph.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _record_acquire(witness: "LockWitness") -> None:
    stack = _held_stack()
    # re-entrant re-acquisition (RLock held lower in the stack) records no
    # edge: by identity, so two same-class instances are never mistaken for
    # re-entrancy
    if stack and not any(w is witness for w in stack):
        top = stack[-1]
        if top.name != witness.name:
            src, dst = top.name, witness.name          # cross-class node
        else:
            src, dst = top.iname, witness.iname        # per-instance node
        with _order_lock:
            edges = _order_edges.setdefault(src, set())
            if dst not in edges:
                edges.add(dst)
                # adding src->new: a pre-existing path new->...->src
                # closes a cycle
                path = _find_path(_order_edges, dst, src)
                if path is not None:
                    cycle = " -> ".join(path + [dst])
                    raise LockOrderError(
                        f"lock-order inversion: acquiring "
                        f"{dst!r} while holding {src!r}, but the "
                        f"process has also seen {cycle}")
    stack.append(witness)


def _record_release(witness: "LockWitness") -> None:
    stack = getattr(_tls, "stack", None) or []
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] is witness:
            del stack[i]
            break


#: monotonically increasing witness serial: per-instance lock-order nodes
#: are named ``ClassName.<attr>#<serial>``
_witness_serial = itertools.count(1)


class LockWitness:
    """Wraps a Lock/RLock; delegates acquire/release to the raw lock (so a
    ``threading.Condition`` built on the same raw lock stays correct) while
    recording ownership and acquisition order."""

    __slots__ = ("raw", "name", "iname", "_owner", "_count")

    def __init__(self, raw, name: str):
        self.raw = raw
        self.name = name
        self.iname = f"{name}#{next(_witness_serial)}"
        self._owner = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self.raw.acquire(blocking, timeout)
        if ok:
            try:
                # only the holding thread mutates these: serialized by raw
                _record_acquire(self)
            except SanitizerError:
                self.raw.release()  # don't leak the raw lock on report
                raise
            self._owner = threading.get_ident()
            self._count += 1
        return ok

    def release(self):
        self._count -= 1
        if self._count <= 0:
            self._owner = None
            self._count = 0
        _record_release(self)
        self.raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    # Condition() probes these on its lock argument
    def _is_owned(self):
        owned = getattr(self.raw, "_is_owned", None)
        if owned is not None:
            return owned()
        return self.held_by_me()

    # Condition.wait() delegates these when its lock provides them: the
    # witness releases (bookkeeping included) around the wait and restores
    # after, keeping ownership tracking exact across waits.
    def _release_save(self):
        saved = self._count
        self._owner = None
        self._count = 0
        _record_release(self)
        inner = getattr(self.raw, "_release_save", None)
        if inner is not None:  # RLock: drop every recursion level at once
            return (inner(), saved)
        self.raw.release()
        return (None, saved)

    def _acquire_restore(self, state):
        raw_state, saved = state
        inner = getattr(self.raw, "_acquire_restore", None)
        if inner is not None:
            inner(raw_state)
        else:
            self.raw.acquire()
        try:
            _record_acquire(self)
        except SanitizerError:
            self.raw.release()
            raise
        self._owner = threading.get_ident()
        self._count = max(1, saved)

    def locked(self):
        return self.raw.locked()

    def __repr__(self):
        return f"<LockWitness {self.name} raw={self.raw!r}>"


# ---------------------------------------------------------------------------
# annotations
# ---------------------------------------------------------------------------

def guarded_by(lock: str | None, *fields: str):
    """Class decorator: declare ``fields`` as shared state guarded by the
    instance lock attribute ``lock`` (e.g. ``"_lock"``).

    ``lock=None`` declares **external serialization**: the class has no lock
    of its own and every mutation must come through a single serialized owner
    (``PageAllocator`` under ``KVBudget``). The static pass then forbids
    direct field writes from outside the class (LOCK-003); the runtime half
    relies on :func:`check_invariants` instead of a witness.

    With ``DLLAMA_SANITIZE`` unset this only records metadata on the class —
    ``__init__`` / ``__setattr__`` are returned untouched.
    """
    def deco(cls):
        guards = dict(getattr(cls, "__guarded_fields__", {}))  # inherit
        for f in fields:
            guards[f] = lock
        cls.__guarded_fields__ = guards
        if _ENABLED and lock is not None:
            _instrument(cls)
        return cls
    return deco


def guard_globals(lock: str, *names: str) -> None:
    """Declare module globals ``names`` guarded by the module-level lock
    ``lock``. Static-analysis metadata only (rule LOCK-004): module globals
    cannot be instrumented without a module ``__setattr__`` hook, and the
    annotated paths are cold."""
    return None


def loop_callback(fn):
    """Annotate ``fn`` as an event-loop callback/coroutine: it runs on the
    single ``selectors`` loop thread (``serving/evloop.py``), where ANY
    blocking call stalls every connection the process is carrying — one
    ``time.sleep`` in a handler is a fleet-wide latency spike.

    dllama-check's LOOP-001 statically forbids the blocking shortlist
    (blocking ``socket.recv/send/connect/accept``, ``time.sleep``,
    no-timeout ``Queue.get``/``.join``, ``conn.getresponse``/``urlopen``)
    inside annotated functions, including their nested ``def``s. The
    audited non-blocking leaf primitives in evloop.py stay UNannotated —
    they are the few lines allowed to touch raw socket calls, and they
    never block (every socket is non-blocking; EAGAIN yields to the loop).

    Metadata-only: returns ``fn`` unchanged (generator-ness preserved)."""
    fn.__loop_callback__ = True
    return fn


def check_invariants(check_method: str, *mutators: str):
    """Class decorator: under ``DLLAMA_SANITIZE=1`` run ``check_method`` after
    every listed mutating method, so the chaos/paged suites execute the
    invariant oracle at every step instead of only where tests remembered to
    call it. Metadata-only (zero wrappers) when the sanitizer is off."""
    def deco(cls):
        cls.__invariant_check__ = (check_method, tuple(mutators))
        if _ENABLED:
            for m in mutators:
                orig = getattr(cls, m)

                def _wrap(orig):
                    @functools.wraps(orig)
                    def run(self, *a, **k):
                        out = orig(self, *a, **k)
                        getattr(self, check_method)()
                        return out
                    return run
                setattr(cls, m, _wrap(orig))
        return cls
    return deco


def _instrument(cls) -> None:
    """Swap declared locks for witnesses post-__init__ and verify guarded
    rebinds hold their lock. Annotated classes must be plain (no __slots__)."""
    guards = cls.__guarded_fields__
    lock_attrs = sorted({l for l in guards.values() if l is not None})
    orig_init = cls.__init__

    @functools.wraps(orig_init)
    def init(self, *a, **k):
        object.__setattr__(self, "_dllama_sanitize_ready", False)
        orig_init(self, *a, **k)
        for lattr in lock_attrs:
            raw = getattr(self, lattr, None)
            if raw is not None and not isinstance(raw, LockWitness):
                object.__setattr__(
                    self, lattr,
                    LockWitness(raw, f"{type(self).__name__}.{lattr}"))
        # a Condition built in __init__ on a now-swapped lock still holds
        # the RAW lock: retarget it onto the witness so wait()'s release/
        # reacquire goes through the witness's bookkeeping (exact ownership
        # across Condition.wait, see module docstring)
        for val in list(vars(self).values()):
            if not isinstance(val, threading.Condition):
                continue
            for lattr in lock_attrs:
                w = getattr(self, lattr, None)
                if isinstance(w, LockWitness) and val._lock is w.raw:
                    val._lock = w
                    val.acquire = w.acquire
                    val.release = w.release
                    val._is_owned = w._is_owned
                    val._release_save = w._release_save
                    val._acquire_restore = w._acquire_restore
                    break
        object.__setattr__(self, "_dllama_sanitize_ready", True)

    cls.__init__ = init
    orig_setattr = cls.__setattr__

    def setattr_(self, name, value):
        lattr = guards.get(name)
        if (lattr is not None
                and self.__dict__.get("_dllama_sanitize_ready", False)):
            w = getattr(self, lattr, None)
            if isinstance(w, LockWitness) and not w.held_by_me():
                raise UnguardedWriteError(
                    f"write to {type(self).__name__}.{name} without "
                    f"{lattr} held (declared guarded_by({lattr!r}))")
        orig_setattr(self, name, value)

    cls.__setattr__ = setattr_
