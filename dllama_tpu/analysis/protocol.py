"""PROTO-00x: wire-contract conformance.

PRs 11-13 grew a cross-process protocol surface — the DKV1 KV-wire
codec, in-band SSE control frames, ``X-Dllama-*`` hop headers, and
federated metric names — where a one-sided edit (writer updated, reader
not) ships a silent fleet-wide bug no single-process unit test can
catch.  All of those strings now live in ``serving/protocol.py``; these
passes cross-check both directions, the way FAULT-001..004 does for
fault sites.

PROTO-001  DKV1 header fields: ``encode_snapshot`` writes vs
           ``decode_snapshot`` parses vs ``DKV1_HEADER_FIELDS``.
PROTO-002  SSE events: every registered event is referenced (via its
           constant) by at least two modules — an emitter and a scanner
           — and no raw event-name literal survives outside the
           registry.
PROTO-003  hop headers: HDR_* constants vs the HOP_HEADERS tuple,
           two-module use, and no raw ``X-Dllama-*``/registered-header
           literal outside the registry.
PROTO-004  metric names: every ``dllama_*`` name consumed somewhere in
           the package is registered via ``counter()``/``gauge()``/
           ``histogram()`` (faults.py's SITE_METRICS is FAULT-003's
           job and exempt here); cli.py — a cross-process consumer that
           scrapes the wire instead of sharing a registry — may not
           spell ANY raw ``dllama_*`` literal (it imports MET_*), and
           the MET_*/WIRE_METRICS registry must itself stay registered.

The registry file is read with ``ast`` — never imported — so the
analyzer stays dependency-free and a syntax error there is an AST-001,
not a crash.
"""

from __future__ import annotations

import ast
import re

from .core import Finding

_PROTO_REL = "serving/protocol.py"
_METRIC_RE = re.compile(r"^dllama_[a-z0-9]+(?:_[a-z0-9]+)+$")
_REGISTRARS = frozenset({"counter", "gauge", "histogram"})


def _is_exempt(rel: str) -> bool:
    """Files allowed to spell wire strings raw: the registry itself and
    the analyzer (rule text quotes examples)."""
    return "/analysis/" in rel or rel.endswith(_PROTO_REL)


def _docstring_nodes(tree) -> set:
    """ids of Constant nodes that are module/class/function docstrings."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                out.add(id(body[0].value))
    return out


class _Registry:
    """The wire-contract registry, parsed (not imported) from
    serving/protocol.py."""

    def __init__(self, proto_src):
        self.src = proto_src
        self.consts: dict = {}   # NAME -> str/bytes value
        self.lines: dict = {}    # NAME -> lineno
        tuples: dict = {}
        for node in proto_src.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            self.lines[name] = node.lineno
            v = node.value
            if isinstance(v, ast.Constant) and isinstance(v.value, (str, bytes)):
                self.consts[name] = v.value
            else:
                tuples[name] = v

        def resolve(n):
            if isinstance(n, ast.Constant):
                return [n.value]
            if isinstance(n, ast.Name):
                if n.id in self.consts:
                    return [self.consts[n.id]]
                if n.id in tuples:
                    return resolve(tuples[n.id])
                return []
            if isinstance(n, ast.Tuple):
                out = []
                for e in n.elts:
                    out.extend(resolve(e))
                return out
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
                return resolve(n.left) + resolve(n.right)
            return []

        def tup(name):
            return tuple(resolve(tuples[name])) if name in tuples else ()

        self.hop_headers = tup("HOP_HEADERS")
        self.sse_events = tup("SSE_EVENTS")
        self.wire_metrics = tup("WIRE_METRICS")
        self.dkv1_fields = tup("DKV1_HEADER_FIELDS")
        self.dkv1_scalars = tup("DKV1_SCALARS")
        self.hdr_consts = {k: v for k, v in self.consts.items()
                           if k.startswith("HDR_")}
        self.sse_consts = {k: v for k, v in self.consts.items()
                           if k.startswith("SSE_EVENT_")}

    def line(self, name: str) -> int:
        return self.lines.get(name, 1)


def _find(sources, suffix):
    for s in sources:
        if s.rel.endswith(suffix):
            return s
    return None


# ---------------------------------------------------------------------------
# PROTO-001: DKV1 header fields
# ---------------------------------------------------------------------------

_SCALAR_TUPLE_NAMES = ("DKV1_SCALARS", "_SCALARS")


def _codec_fields(fn, scalars):
    """(stored, loaded) header-field name sets used inside ``fn``.  A
    reference to the scalar registry tuple counts as touching every
    scalar (both sides loop over it)."""
    stored: set = set()
    loaded: set = set()
    saw_scalars = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in _SCALAR_TUPLE_NAMES:
            saw_scalars = True
        elif (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == "header"
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            bucket = stored if isinstance(node.ctx, ast.Store) else loaded
            bucket.add(node.slice.value)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "header"
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            loaded.add(node.args[0].value)
        elif isinstance(node, ast.Assign):
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "header"
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        stored.add(k.value)
    if saw_scalars:
        stored.update(scalars)
        loaded.update(scalars)
    return stored, loaded


def _check_dkv1(sources, reg):
    kv = _find(sources, "serving/kv_transfer.py")
    if kv is None or not reg.dkv1_fields:
        return []
    enc = dec = None
    for node in ast.walk(kv.tree):
        if isinstance(node, ast.FunctionDef):
            if node.name == "encode_snapshot":
                enc = node
            elif node.name == "decode_snapshot":
                dec = node
    if enc is None or dec is None:
        return []
    fields = set(reg.dkv1_fields)
    written = _codec_fields(enc, reg.dkv1_scalars)[0]
    parsed = _codec_fields(dec, reg.dkv1_scalars)[1]
    findings: list = []
    for f in sorted(fields - written):
        findings.append(Finding(
            "PROTO-001", kv.rel, enc.lineno,
            f"DKV1 field '{f}' is in protocol.DKV1_HEADER_FIELDS but "
            f"encode_snapshot() never writes it"))
    for f in sorted(fields - parsed):
        findings.append(Finding(
            "PROTO-001", kv.rel, dec.lineno,
            f"DKV1 field '{f}' is in protocol.DKV1_HEADER_FIELDS but "
            f"decode_snapshot() never parses it"))
    for f in sorted(written - fields):
        findings.append(Finding(
            "PROTO-001", kv.rel, enc.lineno,
            f"encode_snapshot() writes header field '{f}' that is not in "
            f"protocol.DKV1_HEADER_FIELDS — register it or the reader "
            f"will never see it"))
    for f in sorted(parsed - fields):
        findings.append(Finding(
            "PROTO-001", kv.rel, dec.lineno,
            f"decode_snapshot() parses header field '{f}' that is not in "
            f"protocol.DKV1_HEADER_FIELDS — register it or the writer "
            f"will never send it"))
    return findings


# ---------------------------------------------------------------------------
# PROTO-002 / PROTO-003: constant-reference counting + raw-literal bans
# ---------------------------------------------------------------------------

def _modules_referencing(sources, const_name):
    mods = set()
    for s in sources:
        if s.rel.endswith(_PROTO_REL):
            continue
        for node in ast.walk(s.tree):
            if ((isinstance(node, ast.Name) and node.id == const_name)
                    or (isinstance(node, ast.Attribute)
                        and node.attr == const_name)):
                mods.add(s.rel)
                break
    return mods


def _iter_raw_strings(src):
    """(node, text) for every non-docstring str/bytes Constant."""
    doc = _docstring_nodes(src.tree)
    for node in ast.walk(src.tree):
        if (isinstance(node, ast.Constant) and id(node) not in doc
                and isinstance(node.value, (str, bytes))):
            v = node.value
            if isinstance(v, bytes):
                v = v.decode("utf-8", "replace")
            yield node, v


def _check_sse(sources, reg):
    findings: list = []
    for cname, val in sorted(reg.sse_consts.items()):
        if val not in reg.sse_events:
            findings.append(Finding(
                "PROTO-002", reg.src.rel, reg.line(cname),
                f"{cname} = {val!r} is not listed in SSE_EVENTS"))
        mods = _modules_referencing(sources, cname)
        if len(mods) < 2:
            findings.append(Finding(
                "PROTO-002", reg.src.rel, reg.line(cname),
                f"SSE event {cname} ({val!r}) referenced by {len(mods)} "
                f"module(s) — a wire event needs both an emitter and a "
                f"scanner importing the constant"))
    for s in sources:
        if _is_exempt(s.rel):
            continue
        for node, v in _iter_raw_strings(s):
            hit = next((ev for ev in reg.sse_events if ev and ev in v), None)
            if hit is not None:
                findings.append(Finding(
                    "PROTO-002", s.rel, node.lineno,
                    f"raw SSE event literal {v!r} — import "
                    f"serving/protocol.py's constant for {hit!r} instead"))
            elif v.startswith("event:") and v[len("event:"):].strip():
                findings.append(Finding(
                    "PROTO-002", s.rel, node.lineno,
                    f"SSE frame built from raw literal {v!r} — name the "
                    f"event in serving/protocol.SSE_EVENTS and derive the "
                    f"frame from the constant"))
    return findings


def _check_headers(sources, reg):
    findings: list = []
    hop = set(reg.hop_headers)
    for cname, val in sorted(reg.hdr_consts.items()):
        if val not in hop:
            findings.append(Finding(
                "PROTO-003", reg.src.rel, reg.line(cname),
                f"{cname} = {val!r} is not listed in HOP_HEADERS"))
        mods = _modules_referencing(sources, cname)
        if len(mods) < 2:
            findings.append(Finding(
                "PROTO-003", reg.src.rel, reg.line(cname),
                f"hop header {cname} ({val!r}) referenced by {len(mods)} "
                f"module(s) — a hop header needs both a minter and a "
                f"reader importing the constant"))
    for val in sorted(hop - set(reg.hdr_consts.values())):
        findings.append(Finding(
            "PROTO-003", reg.src.rel, reg.line("HOP_HEADERS"),
            f"HOP_HEADERS entry {val!r} has no HDR_* constant"))
    for s in sources:
        if _is_exempt(s.rel):
            continue
        for node, v in _iter_raw_strings(s):
            if v in hop or (v.startswith("X-Dllama-") and " " not in v):
                findings.append(Finding(
                    "PROTO-003", s.rel, node.lineno,
                    f"raw hop-header literal {v!r} — import the HDR_* "
                    f"constant from serving/protocol.py"))
    return findings


# ---------------------------------------------------------------------------
# PROTO-004: metric names
# ---------------------------------------------------------------------------

def _check_metrics(sources, reg=None):
    registered: set = set()
    registration_nodes: set = set()
    for s in sources:
        for node in ast.walk(s.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRARS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                registered.add(node.args[0].value)
                registration_nodes.add(id(node.args[0]))
    findings: list = []
    for s in sources:
        if _is_exempt(s.rel) or s.rel.endswith("dllama_tpu/faults.py"):
            continue
        cross_process = s.rel.endswith("dllama_tpu/cli.py")
        for node, v in _iter_raw_strings(s):
            if id(node) in registration_nodes or not _METRIC_RE.match(v):
                continue
            if cross_process:
                # cli scrapes the wire instead of sharing a registry, so a
                # registered-elsewhere literal is STILL a desync waiting to
                # happen: the family it spells can be renamed at the
                # registration site without the dashboard noticing
                findings.append(Finding(
                    "PROTO-004", s.rel, node.lineno,
                    f"raw metric literal '{v}' in cli.py — import the "
                    f"MET_* constant from serving/protocol.py so the "
                    f"dashboard can never desync from the registry"))
                continue
            if v in registered:
                continue
            findings.append(Finding(
                "PROTO-004", s.rel, node.lineno,
                f"metric '{v}' consumed here but never registered via "
                f"counter()/gauge()/histogram() — a fleet dashboard would "
                f"read zeros forever"))
    if reg is not None:
        met_consts = {k: v for k, v in reg.consts.items()
                      if k.startswith("MET_")}
        wire = set(reg.wire_metrics)
        for cname, val in sorted(met_consts.items()):
            if val not in wire:
                findings.append(Finding(
                    "PROTO-004", reg.src.rel, reg.line(cname),
                    f"{cname} = {val!r} is not listed in WIRE_METRICS"))
        for val in sorted(wire):
            if val not in registered:
                findings.append(Finding(
                    "PROTO-004", reg.src.rel, reg.line("WIRE_METRICS"),
                    f"WIRE_METRICS entry {val!r} is not registered via "
                    f"counter()/gauge()/histogram() anywhere — the "
                    f"consumer would read zeros forever"))
    return findings


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def check_protocol(sources):
    """All PROTO passes.  Quietly inert when the tree has no registry —
    fixture snippets that never grew a serving/ package stay clean."""
    proto = _find(sources, _PROTO_REL)
    if proto is None:
        return []
    reg = _Registry(proto)
    findings: list = []
    findings.extend(_check_dkv1(sources, reg))
    findings.extend(_check_sse(sources, reg))
    findings.extend(_check_headers(sources, reg))
    findings.extend(_check_metrics(sources, reg))
    return findings
