"""Lock-discipline passes.

LOCK-001  write to a ``guarded_by``-annotated field outside a lexical
          ``with self.<lock>`` block (``__init__`` exempt — construction is
          single-threaded by definition).
LOCK-002  lock-order inversion: the union of every method's lexical
          acquisition nesting forms a directed graph; any cycle means two
          code paths can acquire the same pair of locks in opposite orders.
LOCK-003  direct write to a field of an externally-serialized class
          (``guarded_by(None, ...)``) through a non-``self`` receiver —
          such classes (PageAllocator, RadixPrefixCache) own no lock, so
          every mutation must go through their methods under the owner's
          lock, never by reaching into their attributes.
LOCK-004  write to a ``guard_globals``-declared module global outside a
          ``with <module_lock>`` block.

LOCK-001 is interprocedural since dllama-check v2 (see callgraph.py): a
guarded write inside a private or ``_locked``-suffixed helper is exempt
when EVERY call site in the class provably holds the lock; anything
weaker — a public method, an unlocked call path, a helper with no
in-module caller — is still a finding, now with the offending call chain
in the message.  "The lock is held somewhere up-stack" must be *proved*,
never assumed.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

#: method names that mutate their receiver in place — a call
#: ``self.<field>.append(x)`` counts as a write to <field>
MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "clear", "add", "discard", "update", "setdefault", "appendleft",
    "sort", "reverse",
})


# ---------------------------------------------------------------------------
# annotation harvesting
# ---------------------------------------------------------------------------

def _const_str_or_none(node):
    if isinstance(node, ast.Constant) and (
            node.value is None or isinstance(node.value, str)):
        return True, node.value
    return False, None


def _decorator_guards(cls: ast.ClassDef):
    """{field: lock_or_None} from @guarded_by(...) decorators on ``cls``."""
    guards: dict = {}
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        name = dec.func.attr if isinstance(dec.func, ast.Attribute) else (
            dec.func.id if isinstance(dec.func, ast.Name) else None)
        if name != "guarded_by" or not dec.args:
            continue
        ok, lock = _const_str_or_none(dec.args[0])
        if not ok:
            continue
        for a in dec.args[1:]:
            ok, field = _const_str_or_none(a)
            if ok and field is not None:
                guards[field] = lock
    return guards


def harvest_classes(src: SourceFile) -> dict:
    """{class_name: {field: lock}} with same-module base-class inheritance."""
    classes: dict = {}
    bases: dict = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = _decorator_guards(node)
            bases[node.name] = [b.id for b in node.bases
                                if isinstance(b, ast.Name)]
    # propagate base guards down (derived declarations win)
    for _ in range(len(classes)):
        changed = False
        for name, blist in bases.items():
            for b in blist:
                if b in classes:
                    merged = dict(classes[b])
                    merged.update(classes[name])
                    if merged != classes[name]:
                        classes[name] = merged
                        changed = True
        if not changed:
            break
    return classes


def harvest_global_guards(src: SourceFile) -> dict:
    """{global_name: lock_name} from module-level guard_globals(...) calls."""
    out: dict = {}
    for node in src.tree.body:
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        name = call.func.attr if isinstance(call.func, ast.Attribute) else (
            call.func.id if isinstance(call.func, ast.Name) else None)
        if name != "guard_globals" or len(call.args) < 2:
            continue
        ok, lock = _const_str_or_none(call.args[0])
        if not ok or lock is None:
            continue
        for a in call.args[1:]:
            ok, g = _const_str_or_none(a)
            if ok and g is not None:
                out[g] = lock
    return out


# ---------------------------------------------------------------------------
# write extraction
# ---------------------------------------------------------------------------

def _self_field(node):
    """'f' when ``node`` is ``self.f`` (possibly under subscripts)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _receiver_field(node):
    """(receiver_src, field) for ``<expr>.f`` writes; receiver 'self' or
    a dotted rendering of the expression."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return _dotted(node.value), node.attr
    return None, None


def _dotted(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def iter_writes(body):
    """Yield (node, target) for every write expression in ``body`` —
    Assign/AugAssign/AnnAssign targets, ``del``, and in-place mutator calls.
    ``target`` is the written expression node (Attribute/Subscript/Name)."""
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    for leaf in _unpack(t):
                        yield sub, leaf
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                if getattr(sub, "value", True) is not None:
                    yield sub, sub.target
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    yield sub, t
            elif (isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Attribute)
                  and sub.func.attr in MUTATORS):
                yield sub, sub.func.value


def _unpack(t):
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _unpack(e)
    else:
        yield t


# ---------------------------------------------------------------------------
# LOCK-001 / LOCK-004: guarded writes
# ---------------------------------------------------------------------------

class _WithTracker(ast.NodeVisitor):
    """Walks one function body tracking the lexically-held lock set."""

    def __init__(self, on_write, held0=()):
        self.held: list = list(held0)
        self.on_write = on_write

    def visit_With(self, node: ast.With):
        names = []
        for item in node.items:
            d = _dotted(item.context_expr)
            if d:
                names.append(d)
                self.held.append(d)
        for ctx_item in node.items:
            if ctx_item.optional_vars is not None:
                self.generic_visit(ctx_item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in names:
            self.held.pop()

    visit_AsyncWith = visit_With

    def _write_nodes(self, node):
        self.on_write(node, list(self.held))
        self.generic_visit(node)

    visit_Assign = _write_nodes
    visit_AugAssign = _write_nodes
    visit_AnnAssign = _write_nodes
    visit_Delete = _write_nodes

    def visit_Call(self, node: ast.Call):
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATORS):
            self.on_write(node, list(self.held))
        self.generic_visit(node)

    # nested defs/lambdas run later, when the lexically-visible lock may no
    # longer be held: analyze them with an EMPTY held set — a guarded write
    # inside a callback needs its own lock (or an allow-comment)
    def visit_FunctionDef(self, node):
        inner = _WithTracker(self.on_write, held0=())
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass  # lambdas cannot contain statements, hence no writes


def _writes_from_stmt(stmt, held, guards, lockname_ok, emit):
    """Check one write-bearing statement against the class guards."""
    targets = []
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets.extend(_unpack(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if getattr(stmt, "value", True) is not None:
            targets.append(stmt.target)
    elif isinstance(stmt, ast.Delete):
        targets.extend(stmt.targets)
    elif isinstance(stmt, ast.Call):
        targets.append(stmt.func.value)
    for t in targets:
        field = _self_field(t)
        if field is None or field not in guards:
            continue
        lock = guards[field]
        if lock is None:
            continue  # externally serialized: LOCK-003's job
        if not any(lockname_ok(h, lock) for h in held):
            emit(stmt, field, lock)


def check_guarded_writes(src: SourceFile):
    """LOCK-001 over one file.  Since dllama-check v2 this delegates to
    the interprocedural pass in callgraph.py, which proves "caller always
    holds X" across method boundaries before flagging."""
    from .callgraph import check_guarded_writes as _interprocedural
    return _interprocedural(src)


def check_guarded_globals(src: SourceFile):
    """LOCK-004 over one file: guarded module globals written lock-free."""
    findings: list = []
    gguards = harvest_global_guards(src)
    if not gguards:
        return findings
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        declared = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Global):
                declared.update(sub.names)
        hot = declared & set(gguards)
        if not hot:
            continue

        def on_write(stmt, held, _fn=node):
            targets = []
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    targets.extend(_unpack(t))
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets.append(stmt.target)
            elif isinstance(stmt, ast.Delete):
                targets.extend(stmt.targets)
            for t in targets:
                if not (isinstance(t, ast.Name) and t.id in hot):
                    continue
                lock = gguards[t.id]
                if lock not in held:
                    findings.append(Finding(
                        "LOCK-004", src.rel, stmt.lineno,
                        f"module global {t.id} written in {_fn.name}() "
                        f"outside `with {lock}` (guard_globals)"))

        tracker = _WithTracker(on_write)
        for stmt in node.body:
            tracker.visit(stmt)
    return findings


# ---------------------------------------------------------------------------
# LOCK-003: reaching into externally-serialized classes
# ---------------------------------------------------------------------------

def check_external_writes(sources):
    """LOCK-003 across files: ``x.<field> = ...`` where <field> belongs to a
    guarded_by(None, ...) class and the receiver is not ``self``."""
    external: set = set()
    owners: dict = {}
    for src in sources:
        for cname, guards in harvest_classes(src).items():
            for field, lock in guards.items():
                if lock is None:
                    external.add(field)
                    owners[field] = cname
    findings: list = []
    if not external:
        return findings
    for src in sources:
        for stmt, target in iter_writes(src.tree.body):
            recv, field = _receiver_field(target)
            if field in external and recv not in ("self", "", "cls"):
                findings.append(Finding(
                    "LOCK-003", src.rel, stmt.lineno,
                    f"direct write to {recv}.{field} — {owners[field]} is "
                    f"externally serialized (guarded_by(None)); mutate via "
                    f"its methods under the owner's lock"))
    return findings


# ---------------------------------------------------------------------------
# LOCK-002: acquisition-order graph
# ---------------------------------------------------------------------------

def _lock_node_name(dotted: str, cls_name: str | None, modname: str,
                    known_lock_attrs: set, module_locks: set):
    """Canonical graph-node name for a with-context, or None if the context
    is not a lock (``with open(...)``, ``with mesh:``...)."""
    if not dotted:
        return None
    parts = dotted.split(".")
    leaf = parts[-1]
    is_lockish = ("lock" in leaf.lower()
                  or leaf in known_lock_attrs
                  or (len(parts) == 1 and leaf in module_locks))
    if not is_lockish:
        return None
    if parts[0] == "self":
        owner = cls_name or modname
        return ".".join([owner] + parts[1:])
    if len(parts) == 1:
        return f"{modname}.{leaf}"
    return dotted


def _module_level_locks(src: SourceFile) -> set:
    """Names bound at module level to threading.Lock()/RLock()."""
    out = set()
    for node in src.tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        fn = node.value.func
        leaf = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if leaf in ("Lock", "RLock"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def collect_acquisition_edges(sources):
    """[(src_lock, dst_lock, rel, line)] from lexical with-nesting."""
    edges: list = []
    for src in sources:
        modname = src.rel.rsplit("/", 1)[-1].removesuffix(".py")
        module_locks = _module_level_locks(src)
        lock_attrs = set()
        for guards in harvest_classes(src).values():
            lock_attrs.update(l for l in guards.values() if l)

        def walk(body, cls_name, held):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    walk(node.body, node.name, held)
                    continue
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk(node.body, cls_name, [])
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    acquired = []
                    for item in node.items:
                        lname = _lock_node_name(
                            _dotted(item.context_expr), cls_name, modname,
                            lock_attrs, module_locks)
                        if lname is None:
                            continue
                        for h in held + acquired:
                            if h != lname:
                                edges.append((h, lname, src.rel, node.lineno))
                        acquired.append(lname)
                    walk(node.body, cls_name, held + acquired)
                    continue
                inner = [n for n in ast.iter_child_nodes(node)
                         if isinstance(n, ast.stmt)]
                if inner:
                    walk(inner, cls_name, held)

        walk(src.tree.body, None, [])
    return edges


def _annotation_name(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip("'\"")
    return None


def _per_instance_inversions(sources):
    """LOCK-002 (per-instance): nesting the same lock attribute of two
    *different instances of the same class* in one function.  The graph
    check above canonicalizes ``self.X`` to ``ClassName.X``, so
    ``a._lock`` then ``b._lock`` is one node and never a cycle — yet
    ``a.merge(b)`` racing ``b.merge(a)`` deadlocks.  Only flagged when
    both receivers' classes are known (``self``, an annotated parameter,
    or a local bound to ``ClassName(...)``) and equal — receiver typing
    is otherwise invisible to an AST pass."""
    findings: list = []
    for src in sources:
        known_classes = set(harvest_classes(src))

        def scan_function(fn, cls_name):
            env: dict = {}
            if cls_name is not None:
                env["self"] = cls_name
            args = list(fn.args.posonlyargs) + list(fn.args.args) + \
                list(fn.args.kwonlyargs)
            for a in args:
                ann = _annotation_name(a.annotation)
                if ann:
                    env[a.arg] = ann
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)
                        and isinstance(sub.value.func, ast.Name)
                        and sub.value.func.id in known_classes):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            env[t.id] = sub.value.func.id

            def walk(body, held):
                for node in body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue  # nested defs scanned on their own
                    if isinstance(node, (ast.With, ast.AsyncWith)):
                        acquired = []
                        for item in node.items:
                            d = _dotted(item.context_expr)
                            parts = d.split(".") if d else []
                            if len(parts) != 2 or "lock" not in \
                                    parts[1].lower():
                                continue
                            recv, attr = parts
                            cls = env.get(recv)
                            if cls is None:
                                continue
                            for recv0, attr0, cls0 in held + acquired:
                                if (attr0 == attr and cls0 == cls
                                        and recv0 != recv):
                                    findings.append(Finding(
                                        "LOCK-002", src.rel, node.lineno,
                                        f"per-instance inversion risk in "
                                        f"{fn.name}(): acquiring "
                                        f"{recv}.{attr} while holding "
                                        f"{recv0}.{attr0} — two {cls} "
                                        f"instances; a symmetric call "
                                        f"takes them in the opposite "
                                        f"order. Impose a canonical order "
                                        f"(e.g. sort by id()) or take one "
                                        f"lock at a time"))
                            acquired.append((recv, attr, cls))
                        walk(node.body, held + acquired)
                        continue
                    inner = [n for n in ast.iter_child_nodes(node)
                             if isinstance(n, ast.stmt)]
                    if inner:
                        walk(inner, held)

            walk(fn.body, [])

        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        scan_function(meth, node.name)
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_function(node, None)
    return findings


def check_lock_order(sources):
    """LOCK-002: cycles in the union acquisition graph."""
    edges = collect_acquisition_edges(sources)
    graph: dict = {}
    where: dict = {}
    for a, b, rel, line in edges:
        graph.setdefault(a, set()).add(b)
        where.setdefault((a, b), (rel, line))
    findings: list = []
    reported: set = set()
    for start in sorted(graph):
        path: list = []
        onpath: set = set()
        seen: set = set()

        def dfs(node):
            if node in onpath:
                i = path.index(node)
                cycle = tuple(sorted(path[i:]))
                if cycle not in reported:
                    reported.add(cycle)
                    hops = path[i:] + [node]
                    locs = []
                    for a, b in zip(hops, hops[1:]):
                        rel, line = where[(a, b)]
                        locs.append(f"{a} -> {b} at {rel}:{line}")
                    rel0, line0 = where[(hops[0], hops[1])]
                    findings.append(Finding(
                        "LOCK-002", rel0, line0,
                        "lock-order inversion: " + "; ".join(locs)))
                return
            if node in seen:
                return
            seen.add(node)
            onpath.add(node)
            path.append(node)
            for nxt in sorted(graph.get(node, ())):
                dfs(nxt)
            path.pop()
            onpath.discard(node)

        dfs(start)
    findings.extend(_per_instance_inversions(sources))
    return findings
