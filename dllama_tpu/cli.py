"""dllama-style CLI: ``inference | generate | chat`` on TPU.

Mirrors the reference app surface (`/root/reference/src/apps/dllama/dllama.cpp:195-220`,
flag parser at `/root/reference/src/app.cpp:19-93`). There is no ``worker`` mode:
under SPMD the "workers" are mesh devices of one jitted program — multi-host
topologies come up via ``jax.distributed`` (all hosts run the same command),
not a root/worker socket protocol.

Usage:
    python -m dllama_tpu.cli inference --model m.m --tokenizer t.t \
        --prompt "Hello" --steps 64 --temperature 0.7 --topp 0.9 [--tp 4]
"""

from __future__ import annotations

import argparse
import codecs
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllama_tpu")
    sub = p.add_subparsers(dest="mode", required=True)
    # offline artifact check: no tokenizer, no engine, no device — reads the
    # whole file once against the embedded integrity section (or, on a
    # legacy file without one, proves the size/offset arithmetic only)
    vp = sub.add_parser(
        "verify", help="verify a .m weight file's integrity checksums")
    vp.add_argument("--model", required=True)
    vp.add_argument("--json", action="store_true",
                    help="print the full verification report as JSON")
    vp.add_argument("--shard", default=None, metavar="I/N",
                    help="verify only the row stripe host I of N actually "
                    "loads (tensor-parallel sharded verify): with a DLRB "
                    "row-band section the check reads ~1/N of the file's "
                    "bytes; replicated 1-D tensors are always fully "
                    "checked. Run once per host, e.g. --shard 0/4 ... 3/4")
    def add_router_flags(rp, default_port: int) -> None:
        # shared by `router` (standalone front door) and `fleet` (router +
        # local replicas): the routing policy knobs
        rp.add_argument("--host", default="0.0.0.0")
        rp.add_argument("--port", type=int, default=default_port,
                        help="the front-door listen port")
        rp.add_argument(
            "--probe-interval", type=float, default=1.0, metavar="S",
            help="seconds between /ready probe rounds: drain/crash takes a "
            "replica out of rotation within one interval")
        rp.add_argument(
            "--connect-timeout", type=float, default=2.0, metavar="S",
            help="upstream connect + status-line timeout per hop")
        rp.add_argument(
            "--upstream-timeout", type=float, default=0.0, metavar="S",
            help="upstream response/stream read timeout after the status "
            "line; 0 = unlimited (long decodes stream for minutes)")
        rp.add_argument(
            "--first-byte-timeout", type=float, default=0.0, metavar="S",
            help="deadline for the upstream status line after the request "
            "was sent (the replica's queue+prefill window); 0 falls back "
            "to --upstream-timeout (0 = unlimited)")
        rp.add_argument(
            "--stall-timeout", type=float, default=0.0, metavar="S",
            help="inter-byte stall budget on SSE relay: an upstream "
            "silent past this mid-stream is treated as DEAD and the "
            "stream is checkpoint-resumed on a sibling (counted as "
            "outcome=stall); 0 disables stall detection")
        rp.add_argument(
            "--header-timeout", type=float, default=10.0, metavar="S",
            help="deadline for a client to land a full request head "
            "(the slow-loris kill); 0 = unlimited")
        rp.add_argument(
            "--client-stall-timeout", type=float, default=30.0, metavar="S",
            help="hard kill for clients that stop draining their socket "
            "mid-response: a blocked client write past this closes the "
            "connection (and its upstream within one chunk); 0 = wait "
            "forever (backpressure still pauses the upstream read)")
        rp.add_argument(
            "--max-conns", type=int, default=0, metavar="N",
            help="connection-count admission: at N open client "
            "connections, new ones are shed at accept time with a canned "
            "503 + Retry-After before any state is allocated; 0 = "
            "unlimited")
        rp.add_argument(
            "--probe-read-timeout", type=float, default=2.0, metavar="S",
            help="per-probe READ deadline, distinct from --connect-timeout:"
            " a gray replica (accepts, then silence) costs one read "
            "deadline and is marked circuit-open, never a wedged probe "
            "pass; 0 falls back to --connect-timeout")
        rp.add_argument(
            "--retry-budget", type=int, default=2, metavar="N",
            help="extra replicas tried after a retriable upstream failure "
            "(connect error or 503); 429/504 always pass through untouched")
        rp.add_argument(
            "--affinity-block", type=int, default=256, metavar="BYTES",
            help="prompt-prefix affinity hash block size: repeat "
            "conversations route to the replica whose radix cache holds "
            "their warm KV pages; 0 disables affinity (pure least-load)")
        rp.add_argument(
            "--kv-wire", default="f32", choices=["f32", "q80", "q80+f32"],
            help="wire mode for KV page handoffs (migrations and "
            "mid-stream checkpoints): f32 is bit-exact — a migrated "
            "stream is token-for-token the solo stream; q80 ships ~3.76x "
            "fewer bytes, block-quantized and error-bounded; q80+f32 "
            "ships full pages as q80 but the partial tail page bit-exact "
            "f32 — the page still being decoded into carries no "
            "quantization error, at near-q80 cost")
        rp.add_argument(
            "--ckpt-interval", type=int, default=32, metavar="K",
            help="mid-stream failover: ask each streamed request's "
            "replica for a session checkpoint every K emitted tokens "
            "(token-count based, so deterministic); on an upstream death "
            "mid-SSE the router resumes the stream bit-identically on a "
            "sibling replica from the latest checkpoint. 0 disables "
            "checkpoint frames and resume orchestration")
        rp.add_argument(
            "--ts-interval", type=float, default=1.0, metavar="S",
            help="metrics-history sampling cadence in seconds: a daemon "
            "thread snapshots every counter/gauge/histogram-percentile "
            "into the bounded in-process time-series store behind "
            "GET /metrics/history (under `fleet` the flag also rides "
            "every replica's serve argv, so one flag sets the whole "
            "fleet's history resolution); 0 disables the sampler thread")

    # the fleet front door: stdlib-only, no model artifacts, no jax — it
    # proxies the OpenAI surface across N running `serve` replicas
    rp = sub.add_parser(
        "router", help="stateless HTTP front door over N running replicas")
    rp.add_argument(
        "--replica", action="append", required=True, metavar="HOST:PORT",
        help="one upstream dllama-api replica (repeatable)")
    add_router_flags(rp, default_port=9900)

    # router + N locally spawned/supervised replicas in one command — the
    # test/bench topology (production runs `serve` per machine + `router`)
    fp = sub.add_parser(
        "fleet", help="spawn, supervise and front N local replicas")
    fp.add_argument("--model", required=True)
    fp.add_argument("--tokenizer", required=True)
    fp.add_argument("--replicas", type=int, default=2, metavar="N",
                    help="replica subprocesses to spawn and supervise")
    fp.add_argument("--base-port", type=int, default=9990, metavar="P",
                    help="replica i listens on P+i")
    fp.add_argument("--replica-host", default="127.0.0.1",
                    help="interface the replicas bind (loopback: only the "
                    "router is meant to face traffic)")
    fp.add_argument(
        "--prefill", type=int, default=0, metavar="N",
        help="dedicated prefill replicas (the first N of --replicas, via "
        "a per-replica --role): they run new prompts plus the first decode "
        "chunk, then hand the row's KV pages to a decode replica; goes "
        "with --decode")
    fp.add_argument(
        "--decode", type=int, default=0, metavar="M",
        help="dedicated decode replicas (the next M): they import migrated "
        "KV page streams warm and stream the rest of each completion; "
        "goes with --prefill")
    fp.add_argument(
        "--replica-arg", action="append", default=[], metavar="'--flag v'",
        help="extra `serve` flag(s) passed to every replica (repeatable), "
        "e.g. --replica-arg '--kv-pages 16' --replica-arg '--batch-max 4'")
    fp.add_argument("--max-restarts", type=int, default=3, metavar="N",
                    help="per-replica crash-restart budget; a replica past "
                    "it stays down (the router routes around the hole)")
    fp.add_argument("--ready-timeout", type=float, default=180.0,
                    metavar="S", help="max wait for every replica's first "
                    "/ready 200 (weights load time)")
    fp.add_argument("--drain-timeout", type=float, default=30.0,
                    metavar="S", help="SIGTERM grace per drain: replicas "
                    "finish in-flight work, then the router stops")
    fp.add_argument("--log-dir", default=None, metavar="DIR",
                    help="per-replica stdout/stderr logs (replica-N.log); "
                    "default: inherit this terminal")
    fp.add_argument(
        "--slo-classes", default=None, metavar="SPEC",
        help="per-class SLO lane config passed to every replica's serve "
        "argv (see `serve --slo-classes`); a --replica-arg "
        "'--slo-classes ...' overrides")
    # -- elastic fleet: the closed autoscale loop (serving/autoscale.py
    #    decides, serving/fleet.py's ElasticSupervisor executes) --
    fp.add_argument(
        "--autoscale", action="store_true",
        help="close the loop: evaluate the SLO/pressure policy every "
        "--scale-interval and scale the replica set live between "
        "--min-replicas and --max-replicas (default: the fleet stays at "
        "--replicas forever)")
    fp.add_argument("--min-replicas", type=int, default=1, metavar="N",
                    help="autoscale floor (never drain below this)")
    fp.add_argument("--max-replicas", type=int, default=0, metavar="N",
                    help="autoscale ceiling (0: use --replicas)")
    fp.add_argument("--scale-interval", type=float, default=1.0,
                    metavar="S", help="seconds between policy evaluations")
    fp.add_argument("--scale-up-pressure", type=float, default=0.75,
                    metavar="P", help="fleet pressure at/above which an "
                    "observation counts toward scale-up")
    fp.add_argument("--scale-down-pressure", type=float, default=0.25,
                    metavar="P", help="fleet pressure at/below which a "
                    "quiet observation counts toward scale-down")
    fp.add_argument("--scale-cooldown-up", type=float, default=5.0,
                    metavar="S", help="min seconds between a scale event "
                    "and the next scale-up")
    fp.add_argument("--scale-cooldown-down", type=float, default=20.0,
                    metavar="S", help="min seconds between a scale event "
                    "and the next scale-down")
    fp.add_argument("--prewarm-tokens", type=int, default=16, metavar="N",
                    help="decode budget of each pre-warm prefill replayed "
                    "into a joining replica (must exceed the batch chunk "
                    "or the row finishes before it exports KV pages)")
    add_router_flags(fp, default_port=9900)

    # live fleet terminal view: polls the router's /stats + /metrics/fleet
    # — stdlib only, runs anywhere a curl would
    tp = sub.add_parser(
        "top", help="live terminal view of a running router/fleet")
    tp.add_argument("--router", default="127.0.0.1:9900",
                    metavar="HOST:PORT", help="the router front door")
    tp.add_argument("--interval", type=float, default=1.0, metavar="S",
                    help="seconds between refreshes")
    tp.add_argument("--iterations", type=int, default=0, metavar="N",
                    help="stop after N refreshes (0 = run until ^C)")

    # per-request latency forensics: join trace spans + flight-recorder
    # events already on disk into one phase waterfall — stdlib only
    ep = sub.add_parser(
        "explain", help="phase waterfall for one request id from trace "
        "+ flight-recorder files")
    ep.add_argument("request_id", metavar="REQUEST_ID",
                    help="the X-Request-Id to explain (as logged / "
                    "returned in the response headers)")
    ep.add_argument("--trace", action="append", default=[], metavar="PATH",
                    help="trace file or directory of part files "
                    "(repeatable); the DLLAMA_TRACE output, solo or "
                    "fleet-merged")
    ep.add_argument("--flight", action="append", default=[],
                    metavar="PATH",
                    help="flight-recorder snapshot JSON (a saved "
                    "/debug/flight body or $DLLAMA_FLIGHT dump; "
                    "repeatable)")
    ep.add_argument("--json", action="store_true",
                    help="emit the joined waterfall as JSON")
    ep.add_argument("--width", type=int, default=48, metavar="COLS",
                    help="waterfall bar width in columns")

    # support bundle: one tarball of every observability surface of a
    # running fleet — what you attach to a bug report
    zp = sub.add_parser(
        "snapshot", help="support bundle: tarball the fleet's metrics, "
        "history, stats, alerts, flight rings and newest trace parts")
    zp.add_argument("--router", default="127.0.0.1:9900",
                    metavar="HOST:PORT", help="the router front door")
    zp.add_argument("--out", default=None, metavar="PATH",
                    help="output tarball path (default "
                    "dllama-snapshot-<unixtime>.tar.gz)")
    zp.add_argument("--window", type=float, default=300.0, metavar="S",
                    help="history window to bundle from /metrics/history")
    zp.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="directory holding DLLAMA_TRACE part files; the "
                    "newest part per replica (and overall) is bundled")

    for mode in ("inference", "generate", "chat", "serve", "worker"):
        sp = sub.add_parser(mode)
        if mode == "serve":  # the dllama-api surface (`src/apps/dllama-api`)
            sp.add_argument("--host", default="0.0.0.0")
            sp.add_argument("--port", type=int, default=9990)
            sp.add_argument(
                "--session-cache",
                type=int,
                default=2,
                metavar="N",
                help="conversation KV states kept resident (LRU): N "
                "interleaved chats each reuse their own prefix instead of "
                "re-prefilling; every slot holds a full KV cache in HBM",
            )
            sp.add_argument(
                "--batch-window",
                type=float,
                default=0.0,
                metavar="MS",
                help="arrival window in milliseconds before the scheduler "
                "routes a batch: concurrent requests share every "
                "weight-streaming pass (~Kx throughput under K-way "
                "concurrency, same tokens as solo runs), and later "
                "arrivals join the running pool mid-flight (continuous "
                "batching; streaming rows emit chunk-sized SSE bursts); "
                "0 disables batching entirely",
            )
            sp.add_argument(
                "--batch-max",
                type=int,
                default=8,
                metavar="B",
                help="slot-pool size for continuous batching (HBM bound: "
                "the resident batch cache holds B full-context rows); "
                "requests beyond B queue and are admitted into slots as "
                "earlier rows finish — mid-flight, between decode chunks",
            )
            sp.add_argument(
                "--batch-chunk",
                type=int,
                default=8,
                metavar="N",
                help="fused decode steps per scheduler pass: smaller N "
                "admits queued arrivals into free slots sooner (lower "
                "time-to-first-token under load) at more host round trips; "
                "larger N amortizes dispatch overhead",
            )
            sp.add_argument(
                "--prefill-chunk",
                type=int,
                default=-1,
                metavar="N",
                help="prompt tokens consumed per scheduler tick during "
                "pooled admission: a long prompt no longer stalls resident "
                "rows for its whole prefill — the pool keeps decoding "
                "between N-token pieces, and each piece is bit-identical "
                "to monolithic prefill; -1 = auto (batch-chunk x "
                "batch-max, one decode-chunk's worth of compute), "
                "0 = monolithic",
            )
            sp.add_argument(
                "--kv-buckets",
                type=int,
                default=1,
                metavar="0|1",
                help="length-bucketed KV slot pools (power-of-two ladders "
                "up to seq-len) instead of one uniform full-context slab: "
                "short rows occupy small slabs, so strictly more rows fit "
                "the same HBM budget; rows that outgrow a bucket migrate "
                "to the next slab mid-flight; 0 = uniform full-context "
                "slots (pre-bucketing behavior)",
            )
            sp.add_argument(
                "--kv-bucket-min",
                type=int,
                default=0,
                metavar="N",
                help="smallest KV bucket context length (rounded up to a "
                "power of two); 0 = auto (max(16, 2x batch-chunk))",
            )
            sp.add_argument(
                "--kv-pages",
                type=int,
                default=0,
                metavar="N",
                help="paged KV: tokens per page of one preallocated arena "
                "(halved until it divides seq-len) with per-row page "
                "tables and a copy-on-write radix prefix cache — admits "
                "alias cached shared-prompt pages and prefill only the "
                "uncached tail, growing rows append pages (no slab "
                "migration copies), eviction is LRU under the same "
                "modeled HBM budget; overrides --kv-buckets; 0 = slab "
                "modes (pre-paging behavior)",
            )
            sp.add_argument(
                "--request-timeout",
                type=float,
                default=0.0,
                metavar="S",
                help="per-request wall-clock budget in seconds, counted "
                "from admission (queue time included): an expired request "
                "gets 504 and its decode row is released at the next chunk "
                "boundary; 0 = unlimited",
            )
            sp.add_argument(
                "--queue-depth",
                type=int,
                default=64,
                metavar="N",
                help="max requests in flight (decoding + waiting): overflow "
                "is rejected immediately with 429 + Retry-After instead of "
                "queuing unboundedly",
            )
            sp.add_argument(
                "--slo-classes",
                default=None,
                metavar="SPEC",
                help="per-class SLO lanes for the admission gate and "
                "batch scheduler, e.g. 'interactive:depth=48,deadline=30;"
                "batch:depth=16,resident=2'. Requests pick their lane "
                "with X-Dllama-Class (default interactive). depth bounds "
                "the lane's in-flight count (429 + lane-scoped "
                "Retry-After past it), deadline is the lane's default "
                "wall-clock budget in seconds (outranks "
                "--request-timeout), resident caps the lane's decoding "
                "rows — interactive arrivals preempt batch rows at chunk "
                "boundaries and resume them bit-identically when "
                "pressure drops. Unset = one classless lane "
                "(pre-SLO behavior). Burn-rate targets ride the same "
                "spec: ttft=MS / tpot=MS (per-class p95 latency SLO "
                "targets) and err=FRACTION (5xx error budget) arm the "
                "multi-window burn-rate alert engine behind GET /alerts",
            )
            sp.add_argument(
                "--ts-interval", type=float, default=1.0, metavar="S",
                help="metrics-history sampling cadence in seconds "
                "(see `router --ts-interval`); the sampler also drives "
                "SLO burn-rate evaluation; 0 disables both",
            )
            sp.add_argument(
                "--burn-short", type=float, default=60.0, metavar="S",
                help="short burn-rate window: an SLO alert fires only "
                "when BOTH the short and long windows burn past the "
                "threshold (short reacts, long filters blips)",
            )
            sp.add_argument(
                "--burn-long", type=float, default=300.0, metavar="S",
                help="long burn-rate window (see --burn-short)",
            )
            sp.add_argument(
                "--drain-timeout",
                type=float,
                default=30.0,
                metavar="S",
                help="SIGTERM grace: stop admitting (503), finish live "
                "requests up to S seconds, then exit",
            )
            sp.add_argument(
                "--pid-file",
                default=None,
                metavar="PATH",
                help="write the server pid here (atomic tmp+rename); "
                "removed on shutdown",
            )
            sp.add_argument(
                "--log-json",
                action="store_true",
                help="emit one structured JSON line per finished request "
                "(request id, path, TTFT/TPOT, token counts, finish "
                "reason) to stderr; prompt TEXT is never logged — only "
                "token counts and a sha256 digest — unless --log-prompts",
            )
            sp.add_argument(
                "--log-prompts",
                action="store_true",
                help="include raw prompt text in --log-json records "
                "(privacy default is OFF: logs carry counts and hashes "
                "only)",
            )
            sp.add_argument(
                "--role",
                default="both",
                choices=["prefill", "decode", "both"],
                help="disaggregation role this replica declares on /ready: "
                "'prefill' replicas serve POST /v1/prefill (prompt + first "
                "decode chunk, then export the row's KV pages on the "
                "wire), 'decode' replicas serve POST /v1/kv/import (admit "
                "the migrated row warm and stream the rest); 'both' (the "
                "default) serves end-to-end. Needs --kv-pages for the "
                "migration endpoints; the role is advisory — the router "
                "enforces placement",
            )
            sp.add_argument(
                "--ckpt-interval",
                type=int,
                default=32,
                metavar="K",
                help="mid-stream failover: default checkpoint cadence (in "
                "emitted tokens) for streams the router opts in via the "
                "X-Dllama-Ckpt header without naming its own K; 0 refuses "
                "checkpointing entirely on this replica. Checkpoints need "
                "--kv-pages (the paged pool is what export_row snapshots)",
            )
        sp.add_argument("--model", required=True)
        sp.add_argument("--tokenizer", required=True)
        sp.add_argument("--prompt", default=None)
        sp.add_argument("--steps", type=int, default=64)
        sp.add_argument("--temperature", type=float, default=0.8)
        sp.add_argument("--topp", type=float, default=0.9)
        sp.add_argument("--seed", type=int, default=None)
        sp.add_argument("--dtype", default="bfloat16", choices=["float32", "bfloat16"])
        sp.add_argument(
            "--cache-dtype", default=None,
            choices=[None, "float32", "bfloat16", "f8"],
            help="KV cache element type (default: --dtype). f8 = "
            "float8_e4m3fn: half the cache HBM footprint and read traffic "
            "of bf16 — double the context a chip can hold — at ~3 mantissa "
            "bits of K/V precision (attention still accumulates in f32)",
        )
        sp.add_argument(
            "--tp",
            type=int,
            default=0,
            help="tensor-parallel shards (0 = all visible devices)",
        )
        sp.add_argument("--system-prompt", default=None, help="chat mode system prompt")
        sp.add_argument(
            "--chat-template", default="llama2", choices=["llama2", "llama3"]
        )
        # the reference's wire-compression switch, mapped to ICI collectives
        sp.add_argument(
            "--buffer-float-type",
            default=None,
            choices=["q80", "f32", "f16", "bf16"],
            help="q80: move TP activation gathers as int8 blocks + f32 block "
            "scales over ICI (the reference's Q80 wire compression); "
            "f32/f16/bf16/unset: plain gathers (f16 accepted for reference "
            "command-line compatibility)",
        )
        sp.add_argument(
            "--weights-float-type",
            default=None,
            choices=["q40", "q80", "bf16", "f16", "f32"],
            help="on-device weight storage: q40/q80 keep weights block-quantized "
            "in HBM and matmul through the fused Pallas dequant kernels "
            "(default on TPU: q40 when the model file is q40, else the --dtype); "
            "bf16/f16/f32 dequantize at load",
        )
        sp.add_argument(
            "--tp-overlap",
            action="store_true",
            help="microbatch compute/communication overlap for the batched "
            "TP decode/verify programs: the batch splits into two "
            "half-batches whose ring-scheduled activation gathers hide "
            "under the other half's compute (bit-identical; engages only "
            "when >=2 rows are resident; needs the quantized shard_map TP "
            "path — dense or MoE runs warn and drop to monolithic)",
        )
        sp.add_argument(
            "--tp-reduce",
            default="off",
            choices=["off", "plain", "q80"],
            help="row-parallel reduce direction for wo/w2: each K-shard is "
            "repacked per device, full-width f32 partial sums ride a "
            "pinned-order ppermute ring reduce-scatter, and the residual "
            "add + rmsnorm fold into the scattered shard (the hidden-width "
            "gather disappears). 'plain' keeps a deterministic bit-"
            "reproducible summation order; 'q80' block-quantizes each hop "
            "(~3.6x less reduce wire, error analytically bounded). Needs "
            "the quantized shard_map TP path and shard-granularity-"
            "divisible dims — anything else warns and drops to gather-only",
        )
        sp.add_argument("--nthreads", type=int, default=None, help=argparse.SUPPRESS)
        if mode in ("inference", "generate"):
            sp.add_argument(
                "--profile",
                default=None,
                metavar="DIR",
                help="write a jax.profiler trace of the run to DIR (the TPU "
                "equivalent of the reference's I/T per-task timing split, "
                "`/root/reference/src/utils.cpp:179-182` — open in XProf/"
                "TensorBoard for per-op device timelines)",
            )
        if mode in ("inference", "generate", "serve", "chat"):
            sp.add_argument(
                "--decode-chunk",
                type=int,
                default=None,
                metavar="N",
                help="fused-decode chunk size (default 64): one device "
                "dispatch per N tokens. Bigger amortizes host round trips "
                "(tunneled/remote PJRT); smaller tightens streaming burst "
                "granularity — batched SSE rows emit one burst per chunk",
            )
            sp.add_argument(
                "--spec-draft",
                type=int,
                default=0,
                metavar="K",
                help="prompt-lookup speculative decoding: draft up to K "
                "tokens from the context's own history and verify them in "
                "one device step (emits multiple tokens per weight-streaming "
                "pass on repetitive text; exact — the stream is identical "
                "to plain decode, greedy or sampled — at higher "
                "temperatures drafts are simply accepted less often)",
            )
        # multi-host topology (the reference's `--workers h:p ...` analog,
        # `/root/reference/src/app.cpp:60-80`): under SPMD every host runs the
        # SAME command with its own --host-id; JAX wires the hosts into one
        # mesh over ICI/DCN (no root/worker socket protocol)
        sp.add_argument(
            "--coordinator",
            default=None,
            help="host:port of process 0 for jax.distributed.initialize",
        )
        sp.add_argument("--num-hosts", type=int, default=None)
        sp.add_argument("--host-id", type=int, default=None)
    return p


def write_pid_file(path: str) -> None:
    """Write this process's pid to ``path`` ATOMICALLY (tmp + rename in the
    same directory): a monitor polling the file never reads a half-written
    pid, and a crash mid-write leaves the old file intact."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(f"{os.getpid()}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def maybe_init_distributed(args) -> int:
    """Join the multi-host SPMD job when topology flags are present.

    Returns this process's index (0 in single-host runs). Replaces the
    reference's root-connects-to-workers bootstrap
    (`/root/reference/src/app.cpp:103-112`): there is no weight streaming —
    every host loads its own shard of the weights through its sharded mesh.
    """
    if args.coordinator is None:
        return 0
    if args.num_hosts is None or args.host_id is None:
        raise SystemExit("--coordinator requires --num-hosts and --host-id")
    import jax

    jax.distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_hosts,
        process_id=args.host_id,
    )
    return jax.process_index()


def load_engine(args):
    # flash decode + float8 cache is the one flash configuration not yet
    # hardware-proven: probe the kernel in a SUBPROCESS before this process
    # touches the backend (TPU runtimes are per-process exclusive), so a
    # Mosaic rejection downgrades to dense attention up front instead of
    # crashing the server/chat on its first decode dispatch.
    if (args.cache_dtype == "f8"
            and os.environ.get("DLLAMA_FLASH_DECODE", "0") == "1"):
        from dllama_tpu.ops import flash_decode as _fd

        ok, detail = _fd.probe_kernel(cache="f8")
        if not ok:
            print(f"⚠️  flash-decode f8 probe failed ({detail[:200]}); "
                  "falling back to dense attention (DLLAMA_FLASH_DECODE "
                  "unset)", file=sys.stderr, flush=True)
            os.environ.pop("DLLAMA_FLASH_DECODE", None)

    import jax
    import jax.numpy as jnp

    from dllama_tpu.formats.weights import WeightFileReader
    from dllama_tpu.models import llama
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.runtime.generate import Engine
    from dllama_tpu.runtime.sampler import SamplerConfig
    from dllama_tpu.tokenizer.bpe import Tokenizer

    from dllama_tpu.quants import blocks

    n_tp = args.tp if args.tp > 0 else len(jax.devices())
    t0 = time.time()
    with WeightFileReader(args.model) as reader:
        cfg = ModelConfig.from_spec(reader.spec, dtype=args.dtype)
        print(f"💡 arch: {cfg.arch}")
        print(f"💡 dim: {cfg.dim}  hiddenDim: {cfg.hidden_dim}  nLayers: {cfg.n_layers}")
        print(f"💡 nHeads: {cfg.n_heads}  nKvHeads: {cfg.n_kv_heads}")
        print(f"💡 vocabSize: {cfg.vocab_size}  seqLen: {cfg.seq_len}")
        wft = args.weights_float_type
        if wft is None and jax.default_backend() == "tpu":
            # default to the file's own quantized format: the fused Pallas
            # kernels read 4x fewer HBM bytes/token than bf16 weights. Only
            # on TPU — elsewhere the kernels run in (slow) interpret mode, so
            # quantized residency must be asked for explicitly.
            wft = {blocks.Q40: "q40", blocks.Q80: "q80"}.get(
                reader.spec.weights_float_type
            )
        mesh = None
        if n_tp > 1:
            try:
                from dllama_tpu.parallel.mesh import tp_mesh
            except ImportError as e:
                raise SystemExit(
                    f"tensor-parallel engine unavailable ({e}); pass --tp 1"
                ) from e

            mesh = tp_mesh(n_tp)
        if wft in ("q40", "q80"):
            tp_note = f" x tp={n_tp} (shard_map)" if n_tp > 1 else ""
            print(f"🧮 weights resident as {wft} (fused dequant-matmul kernels){tp_note}")
            # with a mesh, each stacked tensor streams straight into its TP
            # sharding — no device ever holds the whole quantized model.
            # --tp-reduce (when it will engage) streams wo/w2 straight into
            # their per-shard K repacks, skipping an on-device re-pack
            row_stream = False
            if mesh is not None and getattr(args, "tp_reduce", "off") != "off":
                from dllama_tpu.parallel.quant_tp import validate_tp_reduce

                row_stream = validate_tp_reduce(cfg, wft, n_tp) is None
            params = llama.quant_params_from_reader(
                reader, cfg, wft, mesh=mesh, tp_reduce=row_stream)
        else:
            # bf16/f16/f32 request a dense on-device dtype for the weights
            # (dequantized at load when the file is q40/q80)
            dense_dtype = {
                "bf16": jnp.bfloat16,
                "f16": jnp.float16,
                "f32": jnp.float32,
            }.get(wft)
            if mesh is not None:
                # stream tensors straight onto the mesh: peak host memory is
                # one stacked tensor, never the whole model (the 70B case)
                from dllama_tpu.parallel.sharding import sharded_params_from_reader

                params = sharded_params_from_reader(reader, cfg, mesh, dtype=dense_dtype)
            else:
                params = llama.params_from_reader(reader, cfg, dtype=dense_dtype)
    print(f"⏩ loaded weights in {time.time() - t0:.1f}s")

    tok = Tokenizer.from_file(args.tokenizer)
    if args.seed is not None:
        seed = args.seed
    elif jax.process_count() > 1:
        seed = 0  # hosts must agree: per-host time seeds would diverge SPMD
    else:
        seed = int(time.time())
    sampler_cfg = SamplerConfig(temperature=args.temperature, topp=args.topp, seed=seed)
    from dllama_tpu.models.config import resolve_dtype

    cache_dtype = resolve_dtype(args.cache_dtype, default=args.dtype)

    tp_compress = getattr(args, "buffer_float_type", None) == "q80"
    # compression lives in the shard_map quant forward; the dense-weight TP
    # path is pjit (XLA owns its collectives) and cannot honor it
    compress_active = tp_compress and mesh is not None and wft in ("q40", "q80")
    if tp_compress and not compress_active:
        print("⚠️  --buffer-float-type q80 only applies to quantized weights "
              "(q40/q80) under --tp; running plain gathers")
    tp_overlap = bool(getattr(args, "tp_overlap", False))
    if tp_overlap and (mesh is None or wft not in ("q40", "q80")):
        # the Engine would warn-and-drop too; saying it here names the CLI
        # knobs that would turn it on (the Engine only knows its inputs)
        print("⚠️  --tp-overlap needs --tp > 1 with quantized weights "
              "(q40/q80); running monolithic TP programs")
    tp_reduce = getattr(args, "tp_reduce", "off")
    if tp_reduce != "off" and (mesh is None or wft not in ("q40", "q80")):
        print("⚠️  --tp-reduce needs --tp > 1 with quantized weights "
              "(q40/q80); running gather-only TP programs")
    from dllama_tpu.runtime.generate import DECODE_CHUNK

    # explicit None check: an invalid explicit value (e.g. 0) must reach
    # Engine's own validation and error, not silently become the default
    chunk = getattr(args, "decode_chunk", None)
    engine = Engine(cfg, params, sampler_cfg, cache_dtype=cache_dtype, mesh=mesh,
                    tp_compress=compress_active, tp_overlap=tp_overlap,
                    tp_reduce=tp_reduce,
                    decode_chunk=DECODE_CHUNK if chunk is None else chunk)
    if mesh is not None:
        wire = "q80-compressed" if compress_active else "plain"
        overlap = (", microbatch overlap" if engine.tp_overlap_active else "")
        reduce_ = (f", row-parallel {engine.tp_reduce} reduce"
                   if engine.tp_reduce_active else "")
        print(f"🔗 tensor-parallel over {n_tp} devices (ICI mesh, {wire} "
              f"gathers{overlap}{reduce_})")
    return engine, tok, cfg


def run_generate(args, show_stats: bool) -> None:
    engine, tok, cfg = load_engine(args)
    prompt = args.prompt if args.prompt is not None else "Hello"
    tokens = tok.encode(prompt, add_bos=True)
    print(f"📄 prompt tokens: {len(tokens)}")

    profile_dir = getattr(args, "profile", None)
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)

    spec_k = getattr(args, "spec_draft", 0)
    if spec_k:
        stream = engine.generate_spec(
            tokens, args.steps, stop_tokens=(tok.eos_id,), draft_len=spec_k
        )
    else:
        stream = engine.generate(tokens, args.steps, stop_tokens=(tok.eos_id,))

    gen_ms = []
    inf_ms = []
    prev = tokens[-1]
    produced = list()
    try:
        # incremental decode: multi-byte chars can span byte-fallback tokens
        utf8 = codecs.getincrementaldecoder("utf-8")("replace")
        for tok_id, stats in stream:
            piece = tok.decode_piece(prev, tok_id)
            sys.stdout.write(utf8.decode(piece))
            sys.stdout.flush()
            prev = tok_id
            produced.append(tok_id)
            gen_ms.append(stats.generation_ms)
            inf_ms.append(stats.inference_ms)
            if show_stats:
                line = (
                    f"  🔶 G {stats.generation_ms:7.2f} ms "
                    f"I {stats.inference_ms:7.2f} ms "
                    f"T {stats.transfer_ms:7.2f} ms"
                )
                if stats.sent_kb:
                    # the reference's S/R socket-counter columns
                    # (dllama.cpp:74-75); static SPMD schedule -> analytic.
                    # "~" marks the dense-pjit path, where the count is an
                    # ESTIMATE of XLA's all-reduce lowering rather than our
                    # own shard_map collective schedule
                    est = "" if engine.wire_stats_exact else "~"
                    line += (f" S{est} {stats.sent_kb:7.1f} kB"
                             f" R{est} {stats.recv_kb:7.1f} kB")
                sys.stdout.write(line + "\n")
        sys.stdout.write(utf8.decode(b"", True))  # dangling incomplete char -> U+FFFD
        print()
    finally:
        # a failing/interrupted run is the one you most want the trace of
        if profile_dir:
            import jax

            jax.profiler.stop_trace()
            print(f"🔬 profiler trace written to {profile_dir}")
    if gen_ms:
        # skip the first token (prefill) in the average, like the reference
        # averages steady-state decode (`dllama.cpp:86-91`)
        steady = gen_ms[1:] if len(gen_ms) > 1 else gen_ms
        steady_inf = inf_ms[1:] if len(inf_ms) > 1 else inf_ms
        avg = sum(steady) / len(steady)
        avg_inf = sum(steady_inf) / len(steady_inf)
        print(f"Generated tokens:    {len(produced)}")
        print(f"Avg tokens / second: {1000.0 / avg:.2f}")
        print(f"Avg generation time: {avg:.2f} ms")
        print(f"Avg inference time:  {avg_inf:.2f} ms (device)")
        print(f"Avg transfer time:   {avg - avg_inf:.2f} ms (host+dispatch)")
        print(f"Prefill time:        {engine.prefill_ms:.2f} ms ({len(tokens)} tokens)")


def run_chat(args) -> None:
    from dllama_tpu.serving.templates import render_llama2_turn, render_llama3_chat

    spec_k = getattr(args, "spec_draft", 0)
    engine, tok, cfg = load_engine(args)
    system = args.system_prompt
    if system is None:
        system = input("💻 Enter system prompt (optional): ")
    session = None
    all_tokens: list = []  # every token fed or emitted; session pending last
    while True:
        try:
            user = input("👱 User: ")
        except EOFError:
            break
        first = session is None
        used = session.pos if session else 0
        if args.chat_template == "llama3":
            # render only the new turn — prior turns live in the KV cache
            turn = [{"role": "user", "content": user}]
            if first and system:
                turn.insert(0, {"role": "system", "content": system})
            rendered = render_llama3_chat(turn)
        else:
            rendered = render_llama2_turn(user, system or "", first)
        tokens = tok.encode(rendered, add_bos=first)
        if used + len(tokens) + 2 > cfg.seq_len:
            print("(context window exhausted)")
            break
        print("🤖 Assistant: ", end="", flush=True)
        prev = tokens[-1]
        reply = []
        emitted_ids = []
        utf8 = codecs.getincrementaldecoder("utf-8")("replace")
        if spec_k:
            # multi-turn chat is where text repeats; the n-gram index drafts
            # from the whole conversation so far (exact at any temperature)
            stream = engine.generate_spec(
                tokens, args.steps, session=session, stop_tokens=(tok.eos_id,),
                draft_len=spec_k,
                history=all_tokens[:-1] if session else None,
            )
        else:
            stream = engine.generate(
                tokens, args.steps, session=session, stop_tokens=(tok.eos_id,)
            )
        for tok_id, _ in stream:
            emitted_ids.append(tok_id)
            if tok_id == tok.eos_id:
                continue  # generator stops itself after yielding a stop token
            piece = utf8.decode(tok.decode_piece(prev, tok_id))
            print(piece, end="", flush=True)
            prev = tok_id
            reply.append(piece)
        print(utf8.decode(b"", True))
        all_tokens.extend(tokens)
        all_tokens.extend(emitted_ids)
        session = engine.final_session
        if session.pos >= cfg.seq_len - 1:
            print("(context window exhausted)")
            break


def run_worker(args) -> None:
    """SPMD participant for a multi-host run.

    The reference's `dllama worker` binds a port, receives its weight slice,
    and loops on broadcast positions (`/root/reference/src/apps/dllama/
    dllama.cpp:180-193`). Under SPMD there is no asymmetric protocol: a
    "worker" runs the SAME jitted program as the root over the shared mesh,
    so this mode re-runs generate with output suppressed on non-zero hosts.
    Launch every host with identical --model/--prompt/--steps/--seed and a
    unique --host-id; host 0 is the one whose stdout you read.
    """
    if args.coordinator is None:
        raise SystemExit("worker mode requires --coordinator/--num-hosts/--host-id")
    import contextlib
    import io

    ctx = (
        contextlib.redirect_stdout(io.StringIO())
        if args.host_id != 0
        else contextlib.nullcontext()
    )
    with ctx:
        run_generate(args, show_stats=False)


def run_verify(args) -> int:
    """``verify`` mode: open + fully checksum a `.m` file, exit 0/1.

    Three outcomes:
    * structural rejection (truncated/hostile file) — the open itself
      raises, we print the FormatError (which names the first bad tensor
      and byte offset for truncation) and exit 1;
    * checksum mismatch — the report names every failing tensor with its
      byte offset and both CRCs, first corrupt tensor first; exit 1;
    * clean — exit 0 (a legacy file without an integrity section passes
      with the size/offset guarantee only, and says so).

    ``--shard I/N`` restricts the check to host I's row stripe (the bytes
    that host would actually map under N-way tensor parallelism), using the
    DLRB row-band table when the file carries one.
    """
    import json as json_mod

    from dllama_tpu.formats.spec import FormatError
    from dllama_tpu.formats.weights import WeightFileReader

    shard = None
    if getattr(args, "shard", None):
        try:
            i, n = (int(v) for v in args.shard.split("/", 1))
            if not 0 <= i < n:
                raise ValueError
        except ValueError:
            print(f"❌ bad --shard {args.shard!r}: want I/N with 0 <= I < N")
            return 1
        shard = (i, n)
    try:
        with WeightFileReader(args.model) as reader:
            report = reader.verify(shard=shard)
    except FormatError as e:
        if args.json:
            print(json_mod.dumps(
                {"path": args.model, "ok": False, "error": str(e)}))
        else:
            print(f"❌ {args.model}: {e}")
        return 1
    if args.json:
        print(json_mod.dumps(report))
        return 0 if report["ok"] else 1
    if not report["has_integrity"]:
        print(f"⚠️  {args.model}: no integrity section (legacy file) — "
              f"size/offset layout of {report['tensors']} tensors "
              f"({report['payload_bytes']} payload bytes) is consistent, "
              "but payload bytes are UNVERIFIED")
        return 0
    if report["ok"]:
        if shard is not None:
            print(f"✅ {args.model}: shard {report['shard']} — "
                  f"{report.get('bands_checked', 0)} row bands checked "
                  f"({report['tensors']} tensors), all checksums OK")
        else:
            print(f"✅ {args.model}: {report['tensors']} tensors, "
                  f"{report['payload_bytes']} payload bytes, all checksums OK")
        return 0
    for f in report["failures"]:
        where = (f" row band {f['band']}" if "band" in f else "")
        print(f"❌ {args.model}: tensor {f['name']!r}{where} corrupt at byte "
              f"offset {f['offset']} ({f['nbytes']} bytes): stored "
              f"crc32 {f['expected_crc32']}, "
              f"computed {f['actual_crc32']}")
    print(f"{len(report['failures'])} of {report['tensors']} tensors failed")
    return 1


def _top_get(host: str, port: int, path: str, timeout_s: float = 2.0):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _top_fleet_families(text: str) -> dict:
    """Fold a /metrics/fleet exposition into
    {(family, replica): value}, summing counter series and histogram
    ``_sum``/``_count`` lines across their remaining labels."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        if not head:
            continue
        name, _, labels = head.partition("{")
        replica = None
        for part in labels.rstrip("}").split(","):
            if part.startswith('replica="'):
                replica = part[len('replica="'):].rstrip('"')
        if name.endswith("_bucket"):
            continue
        try:
            v = float(value)
        except ValueError:
            continue  # a non-numeric sample (foreign exposition noise)
            #           must not kill a read-only dashboard loop
        key = (name, replica)
        out[key] = out.get(key, 0.0) + v
    return out


def _top_class_series(text: str, families: tuple) -> dict:
    """Fold the named per-class families of a /metrics/fleet exposition
    into {(family, replica, slo_class): value}. The plain families fold
    (:func:`_top_fleet_families`) SUMS across non-replica labels — exactly
    wrong for lane gauges, where interactive and batch pressure must stay
    distinguishable."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        name, _, labels = head.partition("{")
        if name not in families:
            continue
        replica = slo_class = None
        for part in labels.rstrip("}").split(","):
            if part.startswith('replica="'):
                replica = part[len('replica="'):].rstrip('"')
            elif part.startswith('slo_class="'):
                slo_class = part[len('slo_class="'):].rstrip('"')
        try:
            out[(name, replica, slo_class)] = float(value)
        except ValueError:
            continue  # a torn exposition line (replica died mid-write):
            #           skip the sample, the next scrape heals the cell
    return out


_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _spark(values, width: int = 24) -> str:
    """A unicode sparkline of the last ``width`` values (min..max scaled;
    flat series render as a flat low line)."""
    vals = [v for v in values if v is not None][-width:]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK_GLYPHS[int((v - lo) / span * (len(_SPARK_GLYPHS) - 1))]
        for v in vals)


def run_top(args) -> int:
    """``cli top``: a refreshing terminal view of the fleet — per-replica
    rotation/load from the router's /stats, per-replica request counters
    and latency means from /metrics/fleet, firing SLO alerts from
    /alerts and TTFT-p95 sparklines from /metrics/history. Read-only;
    safe against a half-up fleet (unreachable router prints a retry
    line, pre-observability routers just lose the alert/spark rows)."""
    import json as json_mod

    from dllama_tpu.serving.protocol import (MET_CLASS_QUEUE_DEPTH,
                                             MET_CLASS_RESIDENT_ROWS,
                                             MET_FLEET_REPLICAS,
                                             MET_HTTP_REQUESTS,
                                             MET_KV_TRANSFER_BYTES,
                                             MET_SCALE_EVENTS,
                                             MET_TPOT_MS, MET_TTFT_MS)

    host, _, port_s = args.router.rpartition(":")
    if not host or not port_s.isdigit():
        raise SystemExit(f"bad --router {args.router!r}: want HOST:PORT")
    port = int(port_s)
    n = 0
    # last-seen dllama_kv_transfer_bytes_total per replica (value, t): the
    # KV-handoff column is a RATE, so it needs the previous refresh
    kv_prev: dict = {}
    try:
        while True:
            n += 1
            now = time.monotonic()
            lines = []
            try:
                _, stats_body = _top_get(host, port, "/stats")
                stats = json_mod.loads(stats_body)
                code, fleet_body = _top_get(host, port, "/metrics/fleet")
                fleet_text = (fleet_body.decode("utf-8", "replace")
                              if code == 200 else "")
                fams = _top_fleet_families(fleet_text)
                # lane gauges keep their slo_class label (a summed fold
                # would blur interactive and batch pressure together)
                lanes = _top_class_series(
                    fleet_text, (MET_CLASS_QUEUE_DEPTH,
                                 MET_CLASS_RESIDENT_ROWS))
                load = stats.get("load") or {}
                lines.append(
                    f"dllama top — router {args.router}  "
                    f"up {stats.get('uptime_s', 0):.0f}s  "
                    f"replicas {load.get('replicas_ready', '?')}/"
                    f"{load.get('replicas_total', '?')} ready  "
                    f"affinity {stats.get('affinity_entries', 0)}")
                # elastic fleet row: registered size + scale-event
                # counters, rendered only when the router exposes the
                # families (pre-elastic routers just omit the row); every
                # value parse is guarded — a torn /stats body mid-scale
                # must degrade a cell, never kill the dashboard loop
                mets = stats.get("metrics") or {}

                def fam_values(fam):
                    return (mets.get(fam) or {}).get("values") or []

                size_vals = fam_values(MET_FLEET_REPLICAS)
                if size_vals:
                    try:
                        size = f"{float(size_vals[0].get('value')):.0f}"
                    except (TypeError, ValueError):
                        size = "?"
                    events = {}
                    for v in fam_values(MET_SCALE_EVENTS):
                        ev = (v.get("labels") or {}).get("event")
                        try:
                            events[ev] = int(float(v.get("value")))
                        except (TypeError, ValueError):
                            continue  # torn stats value: drop this cell
                    marks = "  ".join(
                        f"{ev} {events[ev]}"
                        for ev in ("joined", "draining", "retired",
                                   "spawn_failed", "prewarm_fallback",
                                   "drain_killed", "injected")
                        if events.get(ev))
                    lines.append(f"elastic: {size} registered  "
                                 + (marks or "no scale events yet"))
                lines.append("")
                lines.append(
                    f"{'replica':<22}{'role':<9}{'state':<10}{'infl':>5}"
                    f"{'occ':>8}{'queue':>7}{'q i/b':>8}{'res i/b':>9}"
                    f"{'kv_free':>9}{'probe_age':>11}"
                    f"{'reqs':>8}{'ttft_ms':>9}{'tpot_ms':>9}"
                    f"{'kv_kB/s':>9}")
                for snap in load.get("replicas") or []:
                    name = snap.get("name", "?")
                    state = ("circuit" if snap.get("circuit_open")
                             else "ready" if snap.get("ready") else "down")
                    # a mid-transition lifecycle outranks the probe
                    # verdict in the column: joining/draining is WHY the
                    # replica isn't taking normal traffic
                    lc = snap.get("state")
                    if lc and lc != "active":
                        state = lc
                    rload = snap.get("load") or {}
                    age = snap.get("probed_age_s")

                    def mean(fam):
                        s = fams.get((f"{fam}_sum", name))
                        c = fams.get((f"{fam}_count", name))
                        return f"{s / c:.1f}" if s is not None and c else "-"

                    def lane_pair(fam):
                        # "i/b": the replica's interactive vs batch value
                        # of a lane gauge; "-" until the replica exposes
                        # per-class series (mixed-version fleets)
                        i = lanes.get((fam, name, "interactive"))
                        b = lanes.get((fam, name, "batch"))
                        if i is None and b is None:
                            return "-"
                        return f"{int(i or 0)}/{int(b or 0)}"

                    reqs = fams.get((MET_HTTP_REQUESTS, name))
                    # KV handoff wire rate (in+out summed — the families
                    # fold summed their direction label): delta since the
                    # previous refresh of this replica's bytes counter
                    kv_bytes = fams.get((MET_KV_TRANSFER_BYTES, name))
                    kv_rate = "-"
                    if kv_bytes is not None:
                        last = kv_prev.get(name)
                        kv_prev[name] = (kv_bytes, now)
                        if last is not None and now > last[1]:
                            kv_rate = "{:.1f}".format(
                                (kv_bytes - last[0]) / 1024.0
                                / (now - last[1]))
                    lines.append(
                        f"{name:<22}{snap.get('role', 'both'):<9}{state:<10}"
                        f"{snap.get('inflight', 0):>5}"
                        f"{rload.get('slots_occupied', 0):>4}/"
                        f"{rload.get('slots_total', 0):<3}"
                        f"{rload.get('queue_depth', 0):>7}"
                        f"{lane_pair(MET_CLASS_QUEUE_DEPTH):>8}"
                        f"{lane_pair(MET_CLASS_RESIDENT_ROWS):>9}"
                        f"{rload.get('kv_pages_free', '-'):>9}"
                        f"{(f'{age:.1f}s' if age is not None else '-'):>11}"
                        f"{(f'{reqs:.0f}' if reqs is not None else '-'):>8}"
                        f"{mean(MET_TTFT_MS):>9}"
                        f"{mean(MET_TPOT_MS):>9}"
                        f"{kv_rate:>9}")
                # the SLO burn-rate picture: every firing alert gets its
                # own row; pre-observability routers 404 -> row omitted
                code, alerts_body = _top_get(host, port, "/alerts")
                if code == 200:
                    alerts = json_mod.loads(alerts_body)
                    firing = [
                        (rname, a)
                        for rname, pay in (alerts.get("replicas")
                                           or {}).items()
                        for a in pay.get("alerts") or []
                        if a.get("state") == "firing"]
                    lines.append("")
                    if firing:
                        for rname, a in firing:
                            lines.append(
                                f"🔥 SLO {a.get('slo', '?'):<18}"
                                f"{rname:<22}burn "
                                f"{a.get('short_burn', 0):.2f}/"
                                f"{a.get('long_burn', 0):.2f} "
                                f"(short/long, fires >"
                                f"{alerts.get('threshold', 1.0):g})")
                    else:
                        lines.append("alerts: none firing")
                # TTFT p95 sparkline per replica, from the federated
                # time-series history (empty until samplers have data)
                code, hist_body = _top_get(
                    host, port, "/metrics/history?window=120")
                if code == 200:
                    hist = json_mod.loads(hist_body)
                    spark_key = f"{MET_TTFT_MS}:p95"
                    rows = []
                    # fleet-size trajectory from the router's OWN series
                    # (the registered-replica gauge is router state, so it
                    # lives under "router", not any replica)
                    rseries = ((hist.get("router") or {}).get("series")
                               or {})
                    fpts = rseries.get(MET_FLEET_REPLICAS)
                    if fpts:
                        try:
                            rows.append(
                                f"  {'fleet size':<22}replicas "
                                f"{_spark([p[1] for p in fpts])} "
                                f"{float(fpts[-1][1]):.0f}")
                        except (TypeError, ValueError, IndexError):
                            pass  # torn history payload: drop the row
                    for rname, pay in sorted(
                            (hist.get("replicas") or {}).items()):
                        pts = (pay.get("series") or {}).get(spark_key)
                        if pts:
                            rows.append(f"  {rname:<22}ttft_p95 "
                                        f"{_spark([p[1] for p in pts])} "
                                        f"{pts[-1][1]:.1f}ms")
                    if rows:
                        lines.append("")
                        lines.extend(rows)
            except (OSError, ValueError) as e:
                lines = [f"dllama top — router {args.router} "
                         f"unreachable ({e}); retrying..."]
            sys.stdout.write("\x1b[2J\x1b[H" + "\n".join(lines) + "\n")
            sys.stdout.flush()
            if args.iterations and n >= args.iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0  # ^C is how an interactive top session ends: clean exit


def run_explain(args) -> int:
    """``cli explain <request-id>``: join the request's trace spans
    (replica phases + router hops) and flight-recorder events into one
    phase waterfall. Pure file reader — nothing needs to be running."""
    import json as json_mod

    from dllama_tpu.obsv import forensics

    if not args.trace and not args.flight:
        print("❌ explain needs at least one --trace or --flight input "
              "(the DLLAMA_TRACE file / a saved /debug/flight body)")
        return 1
    wf = forensics.build_waterfall(
        args.request_id,
        forensics.load_trace_events(args.trace),
        forensics.load_flight_events(args.flight))
    if args.json:
        print(json_mod.dumps(wf, indent=2))
        return 0 if (wf["rows"] or wf["events"]) else 1
    print(forensics.render_waterfall(wf, width=args.width))
    return 0 if (wf["rows"] or wf["events"]) else 1


def run_snapshot(args) -> int:
    """``cli snapshot``: one support-bundle tarball of a running fleet —
    /metrics, /metrics/history, /stats, /alerts and /debug/flight from
    the router plus every replica the router knows, and the newest trace
    part per replica when --trace-dir is given. Unreachable targets
    contribute an error note, never abort the bundle."""
    import io
    import json as json_mod
    import tarfile

    from dllama_tpu.obsv import forensics

    host, _, port_s = args.router.rpartition(":")
    if not host or not port_s.isdigit():
        raise SystemExit(f"bad --router {args.router!r}: want HOST:PORT")
    out_path = args.out or f"dllama-snapshot-{int(time.time())}.tar.gz"
    paths = ("/metrics", f"/metrics/history?window={args.window:g}",
             "/stats", "/alerts", "/debug/flight")

    targets = [("router", host, int(port_s))]
    try:
        _, stats_body = _top_get(host, int(port_s), "/stats")
        stats = json_mod.loads(stats_body)
        for snap in (stats.get("load") or {}).get("replicas") or []:
            name = snap.get("name") or ""
            rhost, _, rport = name.rpartition(":")
            if rhost and rport.isdigit():
                targets.append((name.replace(":", "-"), rhost, int(rport)))
    except (OSError, ValueError) as e:
        print(f"⚠️  router {args.router} unreachable ({e}); bundling "
              "router errors only")

    n_ok = 0
    with tarfile.open(out_path, "w:gz") as tar:

        def add(arcname: str, data: bytes) -> None:
            info = tarfile.TarInfo(arcname)
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))

        for tname, thost, tport in targets:
            errors = []
            for path in paths:
                fname = (path.split("?", 1)[0].strip("/")
                         .replace("/", "-") or "root")
                try:
                    code, body = _top_get(thost, tport, path,
                                          timeout_s=5.0)
                except (OSError, ValueError) as e:
                    errors.append(f"GET {path}: {e}")
                    continue
                if code != 200:
                    errors.append(f"GET {path}: HTTP {code}")
                    continue
                add(f"{tname}/{fname}", body)
                n_ok += 1
            if errors:
                add(f"{tname}/error.txt",
                    ("\n".join(errors) + "\n").encode())
        if args.trace_dir:
            seen = set()
            # per-replica part (fleet names them .replica-<port>) plus
            # the newest file overall (the merged/solo trace)
            hints = [None] + [str(t[2]) for t in targets[1:]]
            for hint in hints:
                p = forensics.newest_trace_part(args.trace_dir, hint=hint)
                if p and p not in seen:
                    seen.add(p)
                    try:
                        with open(p, "rb") as fh:
                            add(f"trace/{os.path.basename(p)}", fh.read())
                    except OSError:
                        pass  # a part rotating away mid-bundle is fine
    print(f"📦 {out_path}: {n_ok} document(s) from {len(targets)} "
          f"target(s)")
    return 0 if n_ok else 1


def main(argv=None) -> None:
    # DLLAMA_PLATFORM=cpu|tpu forces the JAX backend via jax.config — unlike
    # the JAX_PLATFORMS env var this works even when a sitecustomize has
    # already imported jax and pinned a different platform
    platform = os.environ.get("DLLAMA_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    args = build_parser().parse_args(argv)
    if args.mode == "verify":
        # pure host-side file check: no device, no distributed init
        raise SystemExit(run_verify(args))
    if args.mode == "router":
        # stdlib networking only: no device, no distributed init, no jax
        from dllama_tpu.serving.router import run_router

        run_router(args)
        return
    if args.mode == "fleet":
        # the supervisor itself is jax-free; replicas import jax in their
        # own subprocesses
        from dllama_tpu.serving.fleet import run_fleet

        run_fleet(args)
        return
    if args.mode == "top":
        # read-only observer: stdlib HTTP polling, no device, no jax
        raise SystemExit(run_top(args))
    if args.mode == "explain":
        # offline forensics join over trace/flight files: no jax
        raise SystemExit(run_explain(args))
    if args.mode == "snapshot":
        # read-only observer + tarfile: no device, no jax
        raise SystemExit(run_snapshot(args))
    maybe_init_distributed(args)
    if args.mode == "chat":
        run_chat(args)
    elif args.mode == "serve":
        from dllama_tpu.serving.api_server import serve

        serve(args)
    elif args.mode == "worker":
        run_worker(args)
    else:
        run_generate(args, show_stats=args.mode == "inference")


if __name__ == "__main__":
    main()
