"""Decode engine: jitted prefill + single-token decode steps with a resident
KV cache, per-token timing stats, and on-device sampling.

This subsumes the reference's `Inference::infer` loop
(`/root/reference/src/tasks.cpp:199-215`) and the per-token stats surface the
CLI prints (`/root/reference/src/apps/dllama/dllama.cpp:43-92`). Differences
by design, all TPU-motivated:

* The prompt is processed in *batched* prefill (bucketed padded lengths, so a
  handful of compiles serve any prompt) instead of one forward per token.
* One jitted program covers embed -> all layers -> logits -> sample; the host
  sees 4 bytes (the token id) per step, not the logits.
* The KV cache is donated between steps, so XLA updates it in place in HBM.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu import faults, observability
from dllama_tpu.models import llama
from dllama_tpu.models.config import ModelConfig
from dllama_tpu.runtime import paged_kv
from dllama_tpu.runtime.sampler import SamplerConfig, sample_dynamic

PREFILL_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)
DECODE_CHUNK = 64  # fused-loop chunk size: one compile serves any steps count

#: sentinel for Engine(metrics=...): "the shared default registry"
DEFAULT_METRICS = object()


class NumericHealthError(RuntimeError):
    """The decode-step watchdog saw non-finite logits (NaN/Inf from corrupt
    weights, a bad kernel, or hardware error). Solo decode fails fast with
    this; a BatchSession quarantines the poisoned row instead (finish reason
    ``"error"``) and the server maps it to a 500 / ``finish_reason:"error"``
    SSE event."""

    def __init__(self, where: str):
        super().__init__(f"non-finite logits detected {where}; "
                         f"output is unusable from this point")
        self.where = where


def prefill_bucket(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    return n


def dense_stack_wire_feat_bytes(cfg: ModelConfig, hidden: int,
                                per_feat: float, tp_reduce=None) -> float:
    """Modeled per-row wire bytes (before the (tp-1)/tp ring fraction) the
    dense layer stack's collectives carry in one forward — the analytic
    model Engine._wire_bytes and BENCH_REDUCE share, so the benchmark's
    reported delta IS the serving model's delta.

    Gather-only: 4 all-gathers per layer (heads, wo out, padded hidden,
    w2 out) at ``per_feat`` bytes/feature (1.125 under q80 wire
    compression).  Row-parallel (``tp_reduce``): per layer 2 normalized
    gathers (dim each, still ``per_feat``) + 2 reduce-scatters (dim each —
    f32 partials at 4 B/feature, or 1.125 under q80 hop compression) + 2
    scalar f32 psums for the fused rmsnorm, plus one extra final-norm
    gather and psum per forward.  The hidden-width gather — the widest
    collective of the gather-only schedule — disappears entirely."""
    if not tp_reduce:
        return cfg.n_layers * (3 * cfg.dim + hidden) * per_feat
    red_feat = 1.125 if tp_reduce == "q80" else 4.0
    gather_feats = (2 * cfg.n_layers + 1) * cfg.dim
    reduce_feats = 2 * cfg.n_layers * cfg.dim
    psum_scalars = (2 * cfg.n_layers + 1) * 4.0
    return (gather_feats * per_feat + reduce_feats * red_feat + psum_scalars)


@dataclasses.dataclass
class TokenStats:
    """Per-token timing — the reference's G/I/T/S/R line
    (`/root/reference/src/utils.cpp:179-182`, socket counters
    `/root/reference/src/socket.cpp:266-271`, printed at
    `/root/reference/src/apps/dllama/dllama.cpp:74-75`), re-based on what the
    boundaries actually are on TPU:

    * ``generation_ms`` (G): total wall time for the token.
    * ``inference_ms`` (I): time spent waiting on the device program — the
      on-chip compute (including, under TP, the ICI collectives XLA fused in).
    * ``transfer_ms`` (T): G - I — host work + dispatch/launch latency, the
      host<->device round trip that replaces the reference's Ethernet hops.
    * ``sent_kb`` / ``recv_kb`` (S/R): per-device ICI bytes this token's
      collectives move. The reference reads socket counters; under SPMD the
      collective schedule is static, so these are computed analytically
      (ring all-gather: each device sends and receives (tp-1)/tp of every
      gathered feature vector — see Engine._wire_bytes_per_token).
    """

    generation_ms: float
    inference_ms: float
    transfer_ms: float = 0.0
    sent_kb: float = 0.0
    recv_kb: float = 0.0


@dataclasses.dataclass
class Session:
    """Conversation state carried across generate() calls (chat mode).

    ``pending_token`` is the last sampled token, which has NOT yet been fed
    through the model — the next call must consume it first so the KV cache
    sees every conversation token exactly once (the reference feeds every
    sampled token back through ``infer``, including EOS —
    `/root/reference/src/apps/dllama/dllama.cpp:152-166`).
    """

    cache: dict
    pos: int
    pending_token: Optional[int] = None


class Engine:
    """Holds device-resident params + cache and the compiled step functions."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        sampler_cfg: SamplerConfig = SamplerConfig(),
        cache_dtype=jnp.float32,
        mesh=None,
        fuse_quant: bool = True,
        tp_compress: bool = False,
        tp_overlap: bool = False,
        tp_reduce: str = "off",
        decode_chunk: int = DECODE_CHUNK,
        numeric_checks: bool = True,
        metrics=DEFAULT_METRICS,
    ):
        """``mesh``: a 1-D ``tp`` Mesh (see parallel.mesh.tp_mesh) to run
        tensor-parallel — params are placed with the reference's row/col
        slicing as NamedShardings and XLA emits the AllReduces the reference
        hand-rolls as broadcast+gather+root-sum.

        ``tp_overlap``: compile microbatch-overlap variants of the batched
        decode / spec-verify TP programs alongside the monolithic ones
        (llama.forward_batched_overlap): the batch splits into two
        half-batches whose per-layer gathers are ring-scheduled
        (collectives.RingAxis) so one microbatch's wire time hides under
        the other's compute. Bit-identical to the monolithic programs; a
        dispatch engages the overlap program only when >= 2 rows are
        resident (see batch_loop/paged_loop/verify_program). Requested but
        unavailable combinations (no mesh, dense-pjit TP, MoE) warn and
        drop to monolithic — ``tp_overlap_active``/``tp_overlap_reason``
        record the resolution machine-visibly (the server surfaces them
        on /stats).

        ``tp_reduce`` ('off' | 'plain' | 'q80'): row-parallel reduce
        direction — wo/w2 K-shard (parallel.quant_tp.row_shard_quant_leaf),
        their full-width f32 partial sums ride a pinned-order ppermute ring
        reduce-scatter (collectives.reduce_scatter_columns; 'q80'
        block-quantizes each hop's payload), and the residual add + rmsnorm
        fold into the scattered shard so the next gather carries
        already-normalized data. 'plain' keeps a deterministic summation
        order (bit-reproducible run to run); 'q80' trades an analytically
        bounded per-hop error for ~3.6x less reduce-direction wire.
        Requested but unavailable combinations (no mesh, dense-pjit TP,
        MoE, shard granularity misfit) warn and drop to the gather-only
        programs — ``tp_reduce_active``/``tp_reduce_reason`` record the
        resolution machine-visibly, like ``tp_overlap``'s. Composes with
        ``tp_overlap``: each microbatch's reduce-scatters are ring hops
        already, so they interleave exactly like the ring gathers.

        ``numeric_checks``: fuse the numeric-health watchdog — an
        ``isfinite(logits)`` per-row flag — into every decode step (plus the
        ``logits:nan`` fault-injection seam). Elementwise over [B, vocab],
        dwarfed by the [vocab, dim] classifier matmul; BENCH_INTEGRITY
        measures the overhead (<1% target). Off only for that A/B.

        ``metrics``: an observability.MetricsRegistry to record prefill /
        decode-chunk wall times, spec-decode acceptance, and watchdog
        quarantines into. Defaults to the shared default registry; pass
        ``None`` to disable all engine telemetry (the BENCH_OBS A/B
        baseline) — the disabled hot path is a single ``is not None``
        check per handle."""
        if decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, got {decode_chunk}")
        if metrics is DEFAULT_METRICS:
            metrics = observability.default_registry()
        self.metrics = metrics
        if metrics is not None:
            self._m_prefill = metrics.histogram(
                "dllama_prefill_ms", "Prompt prefill wall time per request")
            self._m_step = metrics.histogram(
                "dllama_decode_step_ms",
                "Per-token decode wall time (solo streaming path)")
            self._m_chunk = metrics.histogram(
                "dllama_decode_chunk_ms",
                "Fused decode-chunk wall time (fused/batched/pooled paths)")
            self._m_prefill_chunk = metrics.histogram(
                "dllama_prefill_chunk_ms",
                "Incremental prefill chunk wall time (chunked admission)")
            self._m_migrations = metrics.counter(
                "dllama_kv_migrations_total",
                "Pooled rows migrated to the next larger KV bucket")
            self._m_quarantine = metrics.counter(
                "dllama_numeric_quarantines_total",
                "Rows/streams stopped by the numeric-health watchdog")
            self._m_spec_steps = metrics.counter(
                "dllama_spec_verify_steps_total",
                "Speculative-decode verify launches")
            self._m_spec_accepted = metrics.counter(
                "dllama_spec_drafts_accepted_total",
                "Draft tokens accepted by speculative verify")
            self._m_spec_emitted = metrics.counter(
                "dllama_spec_tokens_emitted_total",
                "Tokens emitted by speculative decode paths")
            self._m_prefix_hits = metrics.counter(
                "dllama_prefix_cache_hits_total",
                "Paged admissions that aliased at least one cached KV page")
            self._m_prefix_misses = metrics.counter(
                "dllama_prefix_cache_misses_total",
                "Paged admissions with no cached prefix page to alias")
            self._m_prefix_tokens = metrics.counter(
                "dllama_prefix_tokens_matched_total",
                "Prompt tokens served from the radix prefix cache instead "
                "of being re-prefilled")
            self._m_cow = metrics.counter(
                "dllama_kv_cow_copies_total",
                "Boundary KV pages copied (copy-on-write) at paged admission")
            self._m_prefix_evictions = metrics.counter(
                "dllama_prefix_evictions_total",
                "Refcount-zero prefix-cache pages evicted (LRU) to satisfy "
                "an allocation")
            self._m_overlap = metrics.counter(
                "dllama_tp_overlap_chunks_total",
                "Decode/verify dispatches routed through the microbatch "
                "compute/communication-overlap TP programs")
            self._m_reduce = metrics.counter(
                "dllama_tp_reduce_chunks_total",
                "Decode/verify dispatches served by the row-parallel "
                "(K-sharded wo/w2, ring reduce-scatter) TP programs")
        else:
            self._m_prefill = self._m_step = self._m_chunk = None
            self._m_prefill_chunk = self._m_migrations = None
            self._m_quarantine = None
            self._m_spec_steps = self._m_spec_accepted = None
            self._m_spec_emitted = None
            self._m_prefix_hits = self._m_prefix_misses = None
            self._m_prefix_tokens = self._m_cow = None
            self._m_prefix_evictions = self._m_overlap = None
            self._m_reduce = None
        self.cfg = cfg
        self.sampler_cfg = sampler_cfg
        self.mesh = mesh
        self.numeric_checks = numeric_checks
        self._tp_compress = tp_compress
        #: machine-visible wire/overlap resolution (served on /stats):
        #: ``tp_wire`` is what actually crosses the interconnect per gather,
        #: ``tp_overlap_active``/``tp_overlap_reason`` say whether the
        #: microbatch-overlap programs were built and, if not, why the
        #: request was dropped (warn-and-drop, never an error).
        self.tp_wire = "plain"
        self.tp_overlap_active = False
        self.tp_overlap_reason = ("not requested" if not tp_overlap
                                  else "no mesh (single device)")
        if tp_reduce in (None, "off"):
            tp_reduce = None
        elif tp_reduce not in ("plain", "q80"):
            raise ValueError(f"tp_reduce must be 'off', 'plain' or 'q80', "
                             f"got {tp_reduce!r}")
        #: row-parallel reduce-direction resolution, same warn-and-drop
        #: contract as tp_overlap above: ``tp_reduce`` is the resolved mode
        #: ('off' when dropped), active/reason the machine-visible why
        self.tp_reduce = "off"
        self.tp_reduce_active = False
        self.tp_reduce_reason = ("not requested" if tp_reduce is None
                                 else "no mesh (single device)")
        #: decode kernel-fusion resolution, machine-visible like the TP
        #: wire above: what each DLLAMA_* fusion flag resolved to on THIS
        #: engine (served on /stats), so a flag that silently declined —
        #: dense weights, dense-pjit TP — shows up without log scraping
        from dllama_tpu.ops import flash_decode as _flash
        from dllama_tpu.ops import fused_rope_cache as _frc
        from dllama_tpu.ops import qmatmul as _qm
        from dllama_tpu.parallel.quant_tp import has_quant_leaves as _hql

        self.kernel_fusions = {
            "flash_decode": "on" if _flash.flash_enabled() else "off",
            "fuse_norm": (
                "off" if not _qm.norm_fusion_enabled()
                else "on" if _hql(params)
                else "requested (dense weights: no quant projection "
                     "epilogue to fuse into)"),
            "fuse_rope_cache": "on" if _frc.fuse_enabled() else "off",
        }
        # fused-loop chunk: one host round trip per chunk of tokens. Bigger
        # chunks amortize dispatch/sync latency (dominant on tunneled or
        # remote-PJRT setups) at the cost of coarser streaming granularity.
        self.decode_chunk = decode_chunk
        fwd = llama.forward
        fwd_b = llama.forward_batched
        fwd_v = llama.forward_batched_verify
        # prefill-only forward computing the lm_head at ONE row (see
        # llama.forward last_pos): at a 128k vocab the [bucket, vocab]
        # classifier matmul dwarfs the single row prefill consumes. None on
        # the quant-TP path — its shard_map wrappers carry a fixed signature
        # and the vocab-sharded gather wants the full [T, vocab] layout.
        fwd_last = llama.forward
        #: generate_batch_spec availability: single mesh, or quant-TP
        #: shard_map (the dense-pjit mesh path has no verify wrapper)
        self.supports_batch_spec = True
        self._batch_cache_sharding = None
        # microbatch-overlap forward variants (quant-TP shard_map only);
        # stay None when the overlap programs are unavailable or unwanted
        fwd_b_ov = fwd_v_ov = None
        if mesh is not None:
            from dllama_tpu.parallel import quant_tp, sharding as _sh
            from jax.sharding import NamedSharding

            if quant_tp.has_quant_leaves(params):
                # quantized weights x TP: pallas kernels don't auto-partition
                # under pjit, so the forward runs as a shard_map program over
                # output-sharded quant planes (parallel.quant_tp)
                red = None
                if tp_reduce is not None:
                    from dllama_tpu.parallel.mesh import TP as _TP

                    kind = next(
                        (leaf.kind for leaf in jax.tree.leaves(
                            params,
                            is_leaf=lambda x: hasattr(x, "kind"))
                         if hasattr(leaf, "kind")), "q40")
                    why = quant_tp.validate_tp_reduce(
                        cfg, kind, mesh.shape[_TP])
                    if why is not None:
                        self.tp_reduce_reason = why
                        import sys as _sys

                        print(f"dllama: tp_reduce requested but declined "
                              f"({why}); gather-only TP programs used",
                              file=_sys.stderr, flush=True)
                    else:
                        red = tp_reduce
                        self.tp_reduce = tp_reduce
                        self.tp_reduce_active = True
                        self.tp_reduce_reason = "on"
                self.params = quant_tp.shard_quant_params(
                    params, mesh, cfg, tp_reduce=red is not None)
                tp_fwd = quant_tp.make_tp_forward(
                    cfg, mesh, self.params, compress=tp_compress,
                    tp_reduce=red
                )
                tp_fwd_b = quant_tp.make_tp_forward_batched(
                    cfg, mesh, self.params, compress=tp_compress,
                    tp_reduce=red
                )
                tp_fwd_v = quant_tp.make_tp_verify_batched(
                    cfg, mesh, self.params, compress=tp_compress,
                    tp_reduce=red
                )
                if tp_compress:
                    self.tp_wire = "q80"
                if tp_overlap:
                    if cfg.is_moe:
                        # the MoE decode's selected-experts union spans all
                        # rows (llama._check_overlap_split) — a half-batch
                        # would change which experts load
                        self.tp_overlap_reason = (
                            "moe: selected-experts union spans rows")
                        import sys as _sys

                        print("dllama: tp_overlap requested but the model "
                              "is MoE — the selected-experts union spans "
                              "all rows, so the microbatch split is not "
                              "exact; monolithic TP programs used",
                              file=_sys.stderr, flush=True)
                    else:
                        tp_fwd_b_ov = quant_tp.make_tp_forward_batched(
                            cfg, mesh, self.params, compress=tp_compress,
                            overlap=True, tp_reduce=red,
                        )
                        tp_fwd_v_ov = quant_tp.make_tp_verify_batched(
                            cfg, mesh, self.params, compress=tp_compress,
                            overlap=True, tp_reduce=red,
                        )

                        def fwd_b_ov(cfg_, params_, rope_, tokens_, cache_,
                                     pos_):
                            return tp_fwd_b_ov(params_, rope_, cache_,
                                               tokens_, pos_)

                        def fwd_v_ov(cfg_, params_, rope_, tokens_, cache_,
                                     pos_):
                            return tp_fwd_v_ov(params_, rope_, cache_,
                                               tokens_, pos_)

                        self.tp_overlap_active = True
                        self.tp_overlap_reason = "on"

                fwd_last = None

                def fwd(cfg_, params_, rope_, tokens_, cache_, pos_):
                    return tp_fwd(params_, rope_, cache_, tokens_, pos_)

                def fwd_b(cfg_, params_, rope_, tokens_, cache_, pos_):
                    return tp_fwd_b(params_, rope_, cache_, tokens_, pos_)

                def fwd_v(cfg_, params_, rope_, tokens_, cache_, pos_):
                    return tp_fwd_v(params_, rope_, cache_, tokens_, pos_)

            else:
                self.supports_batch_spec = False
                if tp_reduce is not None:
                    self.tp_reduce_reason = (
                        "dense-pjit TP path (row-parallel reduce needs the "
                        "shard_map quant path's K-sharded packs)")
                    import sys as _sys

                    print("dllama: tp_reduce requested but the params are "
                          "dense — the row-parallel programs ride the "
                          "shard_map quant-TP path; gather-only pjit used",
                          file=_sys.stderr, flush=True)
                if tp_overlap:
                    self.tp_overlap_reason = (
                        "dense-pjit TP path (overlap needs the shard_map "
                        "quant path)")
                    import sys as _sys

                    print("dllama: tp_overlap requested but the params are "
                          "dense — the microbatch-overlap programs ride the "
                          "shard_map quant-TP path; monolithic pjit used",
                          file=_sys.stderr, flush=True)
                # dense pjit: forward_batched partitions like forward (the
                # per-row vmap'd attention shards by kv head unchanged).
                # allow_flash=False — GSPMD cannot partition a Pallas custom
                # call, so routing this path into the flash kernel would
                # compile it replicated against an all-gathered cache,
                # destroying the TP scaling the mesh exists for; only the
                # shard_map (quant) path may take flash under a mesh
                self.params = _sh.shard_params(params, mesh, cfg)
                from dllama_tpu.ops.flash_decode import flash_enabled

                if flash_enabled():
                    import sys as _sys

                    print("dllama: DLLAMA_FLASH_DECODE=1 ignored on the "
                          "dense-pjit TP path (Pallas calls don't partition "
                          "under pjit); dense attention used — quantized "
                          "weights take flash under TP via shard_map",
                          file=_sys.stderr, flush=True)
                    self.kernel_fusions["flash_decode"] = (
                        "requested (dense-pjit TP: Pallas calls don't "
                        "partition under pjit)")

                def fwd(cfg_, params_, rope_, tokens_, cache_, pos_):
                    return llama.forward(cfg_, params_, rope_, tokens_,
                                         cache_, pos_, allow_flash=False)

                fwd_last = partial(llama.forward, allow_flash=False)

                def fwd_b(cfg_, params_, rope_, tokens_, cache_, pos_):
                    return llama.forward_batched(cfg_, params_, rope_,
                                                 tokens_, cache_, pos_,
                                                 allow_flash=False)
            self._cache_sharding = NamedSharding(mesh, _sh.cache_spec())
            self._batch_cache_sharding = NamedSharding(
                mesh, quant_tp.batch_cache_spec())
        else:
            from dllama_tpu.parallel.quant_tp import has_quant_leaves

            if fuse_quant and has_quant_leaves(params):
                # fewer, larger fused kernels per layer (exact same math).
                # NOTE: if the leaves are already device-resident, the concat
                # transiently holds originals + fused copies; models near HBM
                # capacity should load pre-fused on host instead
                # (llama.quant_params_from_reader fuse=True does exactly that)
                params = llama.fuse_qkv_ffn(params)
            self.params = jax.tree.map(jnp.asarray, params)
            self._cache_sharding = None
        self.rope = llama.rope_tables(cfg)
        self.cache_dtype = cache_dtype
        self._key = jax.random.PRNGKey(sampler_cfg.seed)
        self._last_prefill_bucket = 1  # rows the latest prefill's gathers moved

        # params/rope MUST be jit arguments, not closure captures: a closed-over
        # sharded array is inlined as a (replicated) constant, silently turning
        # tensor-parallel into full replication with zero collectives.
        # temperature/topp are traced scalars (see sampler.sample_dynamic): one
        # compile serves every per-request sampler setting.
        def _health(logits, poison, ok):
            """Watchdog + fault seam, fused into every decode program: poison
            FIRST (injection must look like a real numeric blowup to the
            check), then fold the row's isfinite flag into ``ok``. Compiles
            to elementwise+reduce over the logits the program already holds."""
            if not numeric_checks:
                return logits, ok
            nan = jnp.asarray(jnp.nan, logits.dtype)
            if logits.ndim == 2 and poison.ndim == 1:  # [B, vocab] rows
                logits = jnp.where(poison[:, None], nan, logits)
                return logits, ok & jnp.all(jnp.isfinite(logits), axis=-1)
            logits = jnp.where(poison, nan, logits)
            return logits, ok & jnp.all(jnp.isfinite(logits))

        @partial(jax.jit, donate_argnums=(2,))
        def _decode_step(params, rope, cache, token, pos, key, temp, topp, poison):
            logits, cache = fwd(cfg, params, rope, token[None], cache, pos)
            logits, ok = _health(logits, poison, jnp.bool_(True))
            nxt = sample_dynamic(logits[0], key, temp, topp)
            return nxt, ok, cache

        @partial(jax.jit, donate_argnums=(2,))
        def _prefill(params, rope, cache, padded_tokens, n_tokens, pos):
            # n_tokens is traced (dynamic slice/index) so one compile serves
            # every prompt length within a bucket
            if fwd_last is not None:
                # lm_head at the final prompt row only ([1, vocab]) — the
                # other bucket-1 rows of logits were never read
                logits, cache = fwd_last(cfg, params, rope, padded_tokens,
                                         cache, pos, last_pos=n_tokens - 1)
                return logits[0], cache
            logits, cache = fwd(cfg, params, rope, padded_tokens, cache, pos)
            return jax.lax.dynamic_index_in_dim(logits, n_tokens - 1, keepdims=False), cache

        @partial(jax.jit, donate_argnums=(2,), static_argnames=("n_steps",))
        def _decode_loop(params, rope, cache, token, pos, key, temp, topp,
                         poison, n_steps):
            """N decode steps fused into ONE device program (lax.scan over
            steps, sampling on device). The host sees one dispatch per N
            tokens instead of per token — essential when host<->device launch
            latency rivals the step itself. ``ok`` accumulates the watchdog
            flag across the chunk's steps."""

            def body(carry, _):
                cache, token, pos, key, ok = carry
                key, sub = jax.random.split(key)
                logits, cache = fwd(cfg, params, rope, token[None], cache, pos)
                logits, ok = _health(logits, poison, ok)
                nxt = sample_dynamic(logits[0], sub, temp, topp)
                return (cache, nxt, pos + 1, key, ok), nxt

            (cache, token, pos, key, ok), toks = jax.lax.scan(
                body, (cache, token, pos, key, jnp.bool_(True)), length=n_steps
            )
            return toks, cache, ok

        def _make_decode_loop_batch(fwd_b):
            """Build the fused batched-decode chunk program around one
            batched forward — called twice under tp_overlap (monolithic
            fwd_b and the microbatch-overlap variant) so both programs run
            the byte-identical scan/sampler/watchdog body."""

            @partial(jax.jit, donate_argnums=(2,),
                     static_argnames=("n_steps",))
            def _decode_loop_batch(params, rope, cache, tokens, pos, keys,
                                   temps, topps, poison, n_steps):
                """N batched decode steps fused into one program: every step
                streams the weights ONCE for all B sequences
                (llama.forward_batched) and samples each row on device. A row
                whose own context fills before the batch's step budget pins
                at slot seq_len-1 (its later tokens are garbage the caller
                discards); other rows are unaffected — no cross-row
                truncation.

                ``keys`` [B, 2] / ``temps`` [B] / ``topps`` [B]: every row
                runs its OWN sampler chain and settings, split once per step
                exactly like the solo paths' ``key, sub = split(key)`` — a
                sampled row seeded like a solo request emits the solo
                request's exact stream (the server batches mixed-sampler
                requests on this invariant).

                ``ok`` [B] accumulates each row's watchdog flag over the
                chunk; a poisoned row's garbage stays confined to its own row
                (per-row sampling, per-row cache slab) — siblings are
                bit-identical."""

                def body(carry, _):
                    cache, toks, pos_, keys_, ok = carry
                    logits, cache = fwd_b(cfg, params, rope, toks, cache,
                                          pos_)
                    logits, ok = _health(logits, poison, ok)
                    split = jax.vmap(jax.random.split)(keys_)  # [B, 2, 2]
                    keys_, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(sample_dynamic)(logits, subs, temps, topps
                                                   ).astype(jnp.int32)
                    pos_ = jnp.minimum(pos_ + 1, jnp.int32(cfg.seq_len - 1))
                    return (cache, nxt, pos_, keys_, ok), nxt

                (cache, toks, pos, keys, ok), out = jax.lax.scan(
                    body,
                    (cache, tokens, pos, keys,
                     jnp.ones(tokens.shape, jnp.bool_)),
                    length=n_steps,
                )
                return out, cache, keys, ok  # out [n_steps, B], ok [B]

            return _decode_loop_batch

        def _make_decode_loop_paged(fwd_b):
            """The paged twin of _make_decode_loop_batch — same
            two-instantiation contract for the overlap variant."""

            @partial(jax.jit, donate_argnums=(2,),
                     static_argnames=("n_steps",))
            def _decode_loop_paged(params, rope, arena, tables, tokens, pos,
                                   keys, temps, topps, poison, n_steps):
                """N batched decode steps over PAGED KV: the resident cache is
                one arena of fixed-size token pages ``{k,v: [L, P, page, kv,
                hd]}`` and ``tables`` [B, nb] maps each row's logical block b
                to a physical page (scratch page 0 pads unallocated tails).

                Each step gathers every row's pages into a contiguous
                [L, B, nb*page, kv, hd] window — logical position i of the row
                IS window index i, so ``forward_batched`` (rope by pos,
                mask by pos, write-before-attend) runs on it unchanged and the
                math is bit-identical to a bucketed slab of ctx=nb*page — then
                scatters back ONLY the page containing the position this step
                wrote. Aliased (prefix-cache) pages are never the written page:
                a live row writes at pos >= prompt_len-1, strictly past every
                fully-shared block, and pinned/done rows resolve to the scratch
                page. Duplicate scatter indices (several pinned rows on
                scratch) are harmless garbage-on-garbage.

                Sampling/health semantics are _decode_loop_batch's exactly:
                per-row key chains split once per step, per-row watchdog ``ok``
                accumulation, pos clamped at the window's last slot."""
                page = arena["k"].shape[2]
                B, nb = tables.shape
                W = nb * page

                def gather(a):
                    w = jnp.take(a, tables, axis=1)  # [L, B, nb, page, kv, hd]
                    return w.reshape(a.shape[0], B, W, a.shape[3], a.shape[4])

                def body(carry, _):
                    arena, toks, pos_, keys_, ok = carry
                    window = jax.tree.map(gather, arena)
                    logits, window = fwd_b(cfg, params, rope, toks, window, pos_)
                    logits, ok = _health(logits, poison, ok)
                    split = jax.vmap(jax.random.split)(keys_)
                    keys_, subs = split[:, 0], split[:, 1]
                    nxt = jax.vmap(sample_dynamic)(logits, subs, temps, topps
                                                   ).astype(jnp.int32)
                    wpos = jnp.clip(pos_, 0, W - 1)  # [B] position written
                    blk = wpos // page
                    phys = jnp.take_along_axis(tables, blk[:, None],
                                               axis=1)[:, 0]  # [B]
                    off = blk * page

                    def scat(a, w):
                        # per row: the page-sized slice of the updated window
                        # holding this step's K/V write, back to its arena page
                        pg = jax.vmap(
                            lambda wb, o: jax.lax.dynamic_slice_in_dim(
                                wb, o, page, axis=1),
                            in_axes=(1, 0), out_axes=1)(w, off)
                        return a.at[:, phys].set(pg)  # [L, B, page, kv, hd]

                    arena = jax.tree.map(scat, arena, window)
                    pos_ = jnp.minimum(pos_ + 1, jnp.int32(W - 1))
                    return (arena, nxt, pos_, keys_, ok), nxt

                (arena, toks, pos, keys, ok), out = jax.lax.scan(
                    body,
                    (arena, tokens, pos, keys,
                     jnp.ones(tokens.shape, jnp.bool_)),
                    length=n_steps,
                )
                return out, arena, keys, ok  # out [n_steps, B], ok [B]

            return _decode_loop_paged

        bsh = (None if self._batch_cache_sharding is None else
               {"k": self._batch_cache_sharding, "v": self._batch_cache_sharding})
        self._batch_cache_init = jax.jit(
            lambda b: llama.init_batch_cache(cfg, b, cache_dtype),
            static_argnums=0, out_shardings=bsh,
        )
        self._bucket_cache_init = jax.jit(
            lambda b, s: llama.init_batch_cache(cfg, b, cache_dtype, seq_len=s),
            static_argnums=(0, 1), out_shardings=bsh,
        )
        self._batch_cache_insert = jax.jit(
            # A single-sequence cache [L, S, kv, hd] into row ``b`` of a slot
            # slab [L, B, ctx, kv, hd]. The slab may be a short-context bucket:
            # only the slab's own context window is copied — by construction
            # the row's prefill never wrote past it (admission places rows in
            # a bucket that covers the prompt).
            lambda bc, c, b: jax.tree.map(
                lambda s, x: jax.lax.dynamic_update_slice(
                    s, jax.lax.slice_in_dim(x, 0, s.shape[2], axis=1)[:, None],
                    (0, b, 0, 0, 0)), bc, c),
            donate_argnums=0,
        )
        self._bucket_cache_migrate = jax.jit(
            # Row ``sb`` of a small-bucket slab into row ``db`` of the next
            # bucket's slab: the copied prefix is the row's entire attended
            # history (pos < src ctx), positions past it are garbage the row
            # overwrites before attending — migration is exact.
            lambda dst, src, sb, db: jax.tree.map(
                lambda d, s: jax.lax.dynamic_update_slice(
                    d, jax.lax.dynamic_slice_in_dim(s, sb, 1, axis=1),
                    (0, db, 0, 0, 0)), dst, src),
            donate_argnums=0,
        )
        self._bucket_cache_grow = jax.jit(
            # Carry an exhausted pool's rows into a double-capacity slab
            # (same context): rows keep their indices, the new tail rows are
            # zero/free. src is NOT donated — on allocation failure the pool
            # must survive untouched.
            lambda dst, src: jax.tree.map(
                lambda d, s: jax.lax.dynamic_update_slice(
                    d, s, (0, 0, 0, 0, 0)), dst, src),
            donate_argnums=0,
        )
        def _pages_to_single(single, arena, pages, ntok):
            """Arena pages ``pages`` [NB] into token positions [0, ntok) of a
            single-sequence staging cache — how a paged admission preloads
            its whole aliased prefix in ONE gather dispatch (it used to loop
            one dispatch per page). ``pages`` may be scratch-padded past the
            prefix (callers pad to a power-of-two count so compiles stay
            O(log max_nb), like the window ladder); the traced ``ntok`` mask
            keeps the padding out of the staging cache."""

            def go(s, a):
                nb, page = pages.shape[0], a.shape[2]
                w = jnp.take(a, pages, axis=1).reshape(
                    a.shape[0], nb * page, a.shape[3], a.shape[4])
                n = min(nb * page, s.shape[1])
                w = jax.lax.slice_in_dim(w, 0, n, axis=1)
                keep = (jnp.arange(n) < ntok)[None, :, None, None]
                head = jax.lax.slice_in_dim(s, 0, n, axis=1)
                return jax.lax.dynamic_update_slice(
                    s, jnp.where(keep, w, head), (0, 0, 0, 0))

            return jax.tree.map(go, single, arena)

        self._pages_to_single = jax.jit(_pages_to_single, donate_argnums=0)

        def _single_to_pages(arena, single, pages, offs):
            """Token blocks [offs[i], offs[i]+page) of a filled staging
            cache into arena pages ``pages[i]`` — a completed prefill's
            fresh tail blocks scattered into the pool in ONE dispatch (the
            staging cache is then dropped). Scratch-padded (page, off=0)
            pairs land harmless garbage on the scratch page, the paged
            decode loop's own duplicate-scatter convention."""

            def go(a, s):
                pg = jax.vmap(
                    lambda o: jax.lax.dynamic_slice(
                        s, (0, o, 0, 0),
                        (s.shape[0], a.shape[2], s.shape[2], s.shape[3]))
                )(offs)  # [M, L, page, kv, hd]
                return a.at[:, pages].set(jnp.moveaxis(pg, 0, 1))

            return jax.tree.map(go, arena, single)

        self._single_to_pages = jax.jit(_single_to_pages, donate_argnums=0)

        def _pages_import(arena, pages, blob):
            """Imported page payloads ``blob`` (leaves [L, M, page, kv, hd]
            — the decoded wire frames of a migrating row) scattered into
            arena pages ``pages`` [M] in ONE dispatch. Scratch-padded
            entries land harmless garbage on the scratch page, like
            _single_to_pages' padding convention."""
            return jax.tree.map(
                lambda a, x: a.at[:, pages].set(x.astype(a.dtype)),
                arena, blob)

        self._pages_import = jax.jit(_pages_import, donate_argnums=0)
        self._page_copy = jax.jit(
            # Arena page ``src`` duplicated into page ``dst``: the
            # copy-on-write boundary — an admission whose prompt ends flush
            # on a cached block takes a private copy of that block (its
            # pending-token position will be rewritten by the first decode
            # step) instead of re-prefilling up to page-1 tokens.
            lambda arena, dst, src: jax.tree.map(
                lambda a: a.at[:, dst].set(
                    jax.lax.dynamic_index_in_dim(a, src, axis=1,
                                                 keepdims=False)), arena),
            donate_argnums=0,
        )

        def _make_verify_batch(fwd_v):
            """Build the batched verify program around one verify forward —
            instantiated for the monolithic and (under tp_overlap) the
            microbatch-overlap variants."""

            @partial(jax.jit, donate_argnums=(2,))
            def _verify_batch(params, rope, cache, tokens, pos):
                """Batched greedy speculative verify: [B, T] candidate rows
                -> every (row, position)'s argmax next token in ONE program —
                the batching and speculation bandwidth wins composed (weights
                stream once for B sequences x T positions). Single mesh or
                quant-TP shard_map (fwd_v resolves to make_tp_verify_batched
                there)."""
                logits, cache = fwd_v(cfg, params, rope, tokens, cache, pos)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

            return _verify_batch

        @partial(jax.jit, donate_argnums=(2,))
        def _verify_step(params, rope, cache, tokens, pos):
            """Speculative verify: feed [pending, draft_1..draft_k] at pos,
            return every position's greedy next token. One device program
            scores k+1 candidate continuations — the MXU sees a T=k+1 batch,
            barely costlier than a single-token step on a bandwidth-bound
            decode (the weights stream once either way)."""
            logits, cache = fwd(cfg, params, rope, tokens, cache, pos)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        @partial(jax.jit, donate_argnums=(2,))
        def _verify_sampled(params, rope, cache, tokens, pos, keys, temp, topp):
            """Sampled speculative verify: position i gets the token that
            sequential decoding would have SAMPLED with keys[i] — so the
            host-side acceptance (draft matches the sampled choice) yields a
            stream bit-identical to plain sampled decode as long as the key
            chain is replayed faithfully (see generate_spec)."""
            logits, cache = fwd(cfg, params, rope, tokens, cache, pos)
            toks = jax.vmap(
                lambda l, k: sample_dynamic(l, k, temp, topp)
            )(logits, keys)
            return toks.astype(jnp.int32), cache

        self._decode_step = partial(_decode_step, self.params, self.rope)
        self._prefill = partial(_prefill, self.params, self.rope)
        # preallocated watchdog/poison flags: python bools would retrace on
        # value change, and a fresh device array per token is host overhead
        self._flag_false = jnp.zeros((), jnp.bool_)
        self._flag_true = jnp.ones((), jnp.bool_)
        self._no_poison: dict = {}  # B -> cached all-False [B] flags
        self._decode_loop = partial(_decode_loop, self.params, self.rope)
        self._decode_loop_batch = partial(
            _make_decode_loop_batch(fwd_b), self.params, self.rope)
        self._decode_loop_paged = partial(
            _make_decode_loop_paged(fwd_b), self.params, self.rope)
        self._verify_step = partial(_verify_step, self.params, self.rope)
        self._verify_batch = partial(
            _make_verify_batch(fwd_v), self.params, self.rope)
        self._verify_sampled = partial(_verify_sampled, self.params, self.rope)
        # overlap twins of the batched programs: same loop bodies around the
        # microbatch-overlap forwards; None when overlap is inactive. A
        # dispatch picks per call via batch_loop/paged_loop/verify_program.
        self._decode_loop_batch_ov = (
            partial(_make_decode_loop_batch(fwd_b_ov), self.params, self.rope)
            if fwd_b_ov is not None else None)
        self._decode_loop_paged_ov = (
            partial(_make_decode_loop_paged(fwd_b_ov), self.params, self.rope)
            if fwd_b_ov is not None else None)
        self._verify_batch_ov = (
            partial(_make_verify_batch(fwd_v_ov), self.params, self.rope)
            if fwd_v_ov is not None else None)

        # compiled once; materializes the cache already-sharded (allocate-then-
        # reshard would transiently put the FULL cache in one device's HBM,
        # the exact OOM tensor parallelism exists to avoid)
        if self._cache_sharding is not None:
            sh = {"k": self._cache_sharding, "v": self._cache_sharding}
            self._init_cache = jax.jit(
                lambda: llama.init_cache(cfg, cache_dtype), out_shardings=sh
            )
        else:
            self._init_cache = jax.jit(lambda: llama.init_cache(cfg, cache_dtype))

        #: per-device ICI kB one decode step moves (the reference's S/R line)
        self._wire_kb_cache: dict = {}
        self.wire_kb_per_token = self.wire_kb(1)
        #: quant-TP counts ITS OWN collective schedule (exact); the dense
        #: pjit path estimates from XLA's canonical all-reduce lowering —
        #: surfaced so the CLI can mark estimated S/R columns as such
        if mesh is None:
            self.wire_stats_exact = True  # vacuous: no wire traffic at all
        else:
            from dllama_tpu.parallel.quant_tp import has_quant_leaves

            self.wire_stats_exact = has_quant_leaves(self.params)

    def wire_kb(self, rows: int) -> float:
        """Per-device ICI kB a T=rows forward (prefill bucket, spec verify
        batch) moves. NOT simply rows x the decode number: an MoE batch whose
        row union can cover every expert (rows*k >= E) takes the dense-combine
        path and gathers E hidden vectors per row instead of k. Memoized —
        _wire_bytes walks the params pytree, far too slow for the per-batch
        dispatch loop."""
        kb = self._wire_kb_cache.get(rows)
        if kb is None:
            kb = self._wire_kb_cache[rows] = self._wire_bytes(rows) / 1024.0
        return kb

    def _wire_bytes(self, rows: int) -> float:
        """Per-device ICI bytes a T=rows forward's collectives move (0
        without a mesh; rows=1 is a decode step). The reference counts wire
        bytes at its sockets; here the collective schedule is static so the
        count is analytic:

        * quantized TP (shard_map, parallel.quant_tp): dense archs run 4 ring
          all-gathers per layer — attention heads (dim), wo output (dim), FFN
          hidden (lane-padded H'), w2 output (dim); MoE archs swap the FFN
          pair for one H' gather per selected expert (k at decode) plus one
          combined-output gather (dim). Plus the f32 logits gather when the
          vocab shards. A ring all-gather moves (tp-1)/tp of
          the full vector through each device, in each direction. Activations
          travel in cfg dtype; Q80 wire compression (tp_compress) ships
          1 byte + 1/8 byte of scale per feature instead — 1.78x less than
          bf16, 3.56x less than f32 (the reference's 4.06x table is f32 with
          slightly different framing overheads).
        * dense TP (pjit): XLA emits ~2 all-reduces per layer (attention out,
          FFN out), each ~2x(tp-1)/tp of dim per device per direction
          (reduce-scatter + all-gather decomposition).
        """
        if self.mesh is None:
            return 0.0
        from dllama_tpu.parallel.mesh import TP
        from dllama_tpu.parallel.quant_tp import ffn_padded_width, has_quant_leaves

        tp = self.mesh.shape[TP]
        if tp <= 1:
            return 0.0
        cfg = self.cfg
        frac = (tp - 1) / tp
        act_bytes = float(jnp.dtype(cfg.jax_dtype).itemsize)
        if has_quant_leaves(self.params):
            from dllama_tpu.ops.qmatmul import _pad_up

            # q80 wire compression ships 1 int8 + 1/8 B of f32 scale per
            # feature regardless of the activation dtype; plain gathers move
            # activations as-is (bf16 or f32 per --dtype)
            per_feat = 1.125 if self._tp_compress else act_bytes
            kind = "q40"
            for leaf in jax.tree.leaves(
                self.params, is_leaf=lambda x: hasattr(x, "kind")
            ):
                if hasattr(leaf, "kind"):
                    kind = leaf.kind
                    break
            hidden = ffn_padded_width(cfg, kind, tp)
            if cfg.is_moe:
                # expert stacks carry output shards like w1/w2/w3. Per layer
                # and per row: 2 attention gathers (dim each), the hidden
                # gather, one combined-output gather (dim). The hidden
                # gather moves min(E, rows*k) expert hiddens for EVERY row —
                # small batches (rows*k < E) run the selected-experts path
                # whose union caps at rows*k experts, each computed for all
                # rows; bigger batches take the dense combine over all E.
                E, k = cfg.n_experts, cfg.n_active_experts
                layer_feats = cfg.n_layers * (
                    3 * cfg.dim + min(E, rows * k) * hidden
                )
                bytes_ = layer_feats * per_feat
            else:
                bytes_ = dense_stack_wire_feat_bytes(
                    cfg, hidden, per_feat,
                    self.tp_reduce if self.tp_reduce_active else None)
            if cfg.vocab_size % tp == 0:
                # the logits gather moves the lane-PADDED vocab (sliced back
                # after the gather), already cast to f32 and never compressed
                bytes_ += _pad_up(cfg.vocab_size, 128 * tp) * 4.0
            return bytes_ * frac * rows
        # dense pjit path: estimated from XLA's canonical all-reduce lowering
        return cfg.n_layers * 2 * cfg.dim * act_bytes * 2 * frac * rows

    def new_cache(self) -> dict:
        return self._init_cache()

    def _overlap_engaged(self, rows: int) -> bool:
        """One overlap dispatch decision: True routes this call through the
        microbatch-overlap program. Engages only when >= 2 rows are live —
        a lone resident row has no second microbatch to hide wire time
        behind, so it takes the monolithic program (same math either way;
        the overlap twin's static batch split is pool-sized regardless).
        Fires the ``overlap_split`` fault seam and counts the engagement
        (dllama_tp_overlap_chunks_total) so A/B replays and the obs drill
        can prove which program served each chunk."""
        if rows < 2:
            return False
        faults.fire("overlap_split")
        if self._m_overlap is not None:
            self._m_overlap.inc()
        return True

    def _reduce_dispatch(self) -> None:
        """Per-dispatch accounting for the row-parallel reduce direction:
        unlike overlap there is no program choice (row mode rebuilds ALL
        the TP programs), so this fires the ``tp_reduce`` fault seam and
        counts the dispatch (dllama_tp_reduce_chunks_total) — the
        machine-visible proof a replay was actually served by the
        reduce-direction programs, scraped by BENCH_REDUCE."""
        if not self.tp_reduce_active:
            return
        faults.fire("tp_reduce")
        if self._m_reduce is not None:
            self._m_reduce.inc()

    def batch_loop(self, rows: int):
        """The fused batched-decode chunk program for a dispatch with
        ``rows`` live rows — the overlap twin when built and engaged,
        else the monolithic program."""
        self._reduce_dispatch()
        if self._decode_loop_batch_ov is not None \
                and self._overlap_engaged(rows):
            return self._decode_loop_batch_ov
        return self._decode_loop_batch

    def paged_loop(self, rows: int):
        """Paged twin of :meth:`batch_loop` (same engagement rule)."""
        self._reduce_dispatch()
        if self._decode_loop_paged_ov is not None \
                and self._overlap_engaged(rows):
            return self._decode_loop_paged_ov
        return self._decode_loop_paged

    def verify_program(self, rows: int):
        """The batched spec-verify program for ``rows`` live rows (see
        :meth:`batch_loop`)."""
        self._reduce_dispatch()
        if self._verify_batch_ov is not None \
                and self._overlap_engaged(rows):
            return self._verify_batch_ov
        return self._verify_batch

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def _poison_flag(self) -> jax.Array:
        """Scalar ``logits:nan`` fault seam for the solo decode programs."""
        fv = faults.fire("logits")
        if fv is not None and fv["action"] == "nan":
            return self._flag_true
        return self._flag_false

    def _poison_rows(self, B: int) -> jax.Array:
        """[B] ``logits:nan`` fault seam for the batched decode programs —
        ``row=N`` selects which row gets poisoned."""
        flags = self._no_poison.get(B)
        if flags is None:
            flags = self._no_poison[B] = jnp.zeros((B,), jnp.bool_)
        fv = faults.fire("logits")
        if fv is not None and fv["action"] == "nan":
            flags = flags.at[min(max(fv["row"], 0), B - 1)].set(True)
        return flags

    def prefill(self, cache: dict, tokens: list, pos: int = 0,
                chunk: Optional[int] = None) -> tuple:
        """Run the prompt starting at ``pos``. Returns (last_logits, cache).

        Tail-padding to a bucket is safe: padded queries produce garbage
        logits we never read, and padded cache slots sit at positions a
        causal query never attends before a real decode overwrites them.

        ``chunk`` splits the prompt into pieces of at most that many tokens,
        each its own bucketed forward at an advancing ``pos`` into the SAME
        cache. Causal attention reads chunk N-1's K/V exactly as the fused
        forward computed them (every forward writes the cache before
        attending), so the chunked result is bit-identical to the monolithic
        one — the split only bounds how long one dispatch can occupy the
        device while a serving pool has resident rows waiting to decode.
        """
        if not 0 < pos + len(tokens) <= self.cfg.seq_len:
            raise ValueError(
                f"prompt of {len(tokens)} tokens at pos {pos} exceeds seq_len {self.cfg.seq_len}"
            )
        faults.fire("prefill")
        if chunk is not None and chunk < 1:
            raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
        if chunk is None or chunk >= len(tokens):
            return self._prefill_piece(cache, tokens, pos)
        logits = None
        for i in range(0, len(tokens), chunk):
            faults.fire("prefill_chunk")
            logits, cache = self._prefill_piece(cache, tokens[i:i + chunk],
                                                pos + i)
        return logits, cache

    def _prefill_piece(self, cache: dict, tokens, pos: int) -> tuple:
        """One bucketed prefill forward (validated by the callers)."""
        # clamp the padded bucket to the remaining context: an out-of-range
        # dynamic_update_slice start would be silently clamped by XLA, writing
        # K/V into wrong slots with wrong rope angles
        bucket = min(prefill_bucket(len(tokens)), self.cfg.seq_len - pos)
        self._last_prefill_bucket = bucket
        padded = np.zeros(bucket, np.int32)
        padded[: len(tokens)] = tokens
        return self._prefill(cache, jnp.asarray(padded), len(tokens), jnp.int32(pos))

    def generate(
        self,
        prompt_tokens: list,
        steps: int,
        session: Optional[Session] = None,
        stop_tokens: tuple = (),
        sampler: Optional[SamplerConfig] = None,
    ) -> Iterator[tuple]:
        """Yield (token_id, TokenStats) for up to ``steps`` generated tokens.

        Pass the previous call's ``engine.final_session`` to continue a
        conversation with one continuous KV cache and position counter (the
        reference keeps one continuous pos across turns,
        `/root/reference/src/apps/dllama/dllama.cpp:154-161`).

        ``sampler`` overrides the engine-level SamplerConfig for this call
        only (per-request temperature/topp/seed, the API-server surface) —
        no recompilation, the settings are traced scalars.
        """
        scfg = sampler if sampler is not None else self.sampler_cfg
        temp, topp = jnp.float32(scfg.temperature), jnp.float32(scfg.topp)
        if sampler is not None:
            local_key = jax.random.PRNGKey(scfg.seed)

            def next_key():
                nonlocal local_key
                local_key, sub = jax.random.split(local_key)
                return sub
        else:
            next_key = self.next_key
        if session is None:
            cache, pos = self.new_cache(), 0
        else:
            cache, pos = session.cache, session.pos
            if session.pending_token is not None:
                prompt_tokens = [session.pending_token] + list(prompt_tokens)
        steps = min(steps, self.cfg.seq_len - pos - len(prompt_tokens))

        t0 = time.perf_counter()
        if len(prompt_tokens) > 1:
            last_logits, cache = self.prefill(cache, prompt_tokens, pos)
            # sample the first generated token from the prefill logits
            token = sample_dynamic(last_logits, next_key(), temp, topp)
        else:
            token = jnp.asarray(prompt_tokens[0], jnp.int32)
        token.block_until_ready()
        self.prefill_ms = (time.perf_counter() - t0) * 1000.0
        if self._m_prefill is not None and len(prompt_tokens) > 1:
            self._m_prefill.observe(self.prefill_ms)

        tok_int: Optional[int] = None
        if len(prompt_tokens) > 1:
            pos += len(prompt_tokens)
            if steps <= 0:
                # caller asked for no tokens (or the context is full): the
                # prefill still advanced the session, but nothing is emitted
                self.final_session = Session(cache, pos, pending_token=None)
                return
            tok_int = int(token)
            # final_session is refreshed BEFORE every yield so a consumer that
            # abandons the generator mid-stream (stop-string hit, client
            # disconnect) still observes the state matching what it received
            self.final_session = Session(cache, pos, pending_token=tok_int)
            # prefill gathers move `bucket` rows of every collective at once
            pf_kb = self.wire_kb(self._last_prefill_bucket)
            yield tok_int, TokenStats(self.prefill_ms, self.prefill_ms,
                                      sent_kb=pf_kb, recv_kb=pf_kb)
            steps -= 1
            if tok_int in stop_tokens:
                return
        for _ in range(max(steps, 0)):
            t1 = time.perf_counter()
            self._reduce_dispatch()  # solo steps ride the row programs too
            token, ok, cache = self._decode_step(
                cache, token, jnp.int32(pos), next_key(), temp, topp,
                self._poison_flag()
            )
            # the call above returns as soon as the program is enqueued; the
            # dispatch wall time is host+launch overhead ("transfer"), the
            # block from here to the result is device execution ("inference")
            t2 = time.perf_counter()
            token.block_until_ready()
            t3 = time.perf_counter()
            if not bool(ok):
                # fail fast: the sampled token is garbage — don't emit it
                if self._m_quarantine is not None:
                    self._m_quarantine.inc()
                raise NumericHealthError(f"at decode position {pos}")
            tok_int = int(token)
            t4 = time.perf_counter()
            dt = (t4 - t1) * 1000.0
            if self._m_step is not None:
                self._m_step.observe(dt)
            pos += 1
            self.final_session = Session(cache, pos, pending_token=tok_int)
            yield tok_int, TokenStats(
                generation_ms=dt,
                inference_ms=(t3 - t2) * 1000.0,
                transfer_ms=(t2 - t1 + t4 - t3) * 1000.0,
                sent_kb=self.wire_kb_per_token,
                recv_kb=self.wire_kb_per_token,
            )
            if tok_int in stop_tokens:
                break
        if tok_int is None:
            # nothing was generated: a 1-token prompt with steps<=0 leaves the
            # prompt token itself unconsumed
            pending = prompt_tokens[0] if len(prompt_tokens) == 1 else None
        else:
            pending = tok_int
        self.final_session = Session(cache, pos, pending_token=pending)

    def generate_fused(
        self, prompt_tokens: list, steps: int, sampler: Optional[SamplerConfig] = None
    ) -> tuple:
        """Batch-generate ``steps`` tokens with the fused on-device loop.

        Returns (tokens list, prefill_ms, decode_ms_total). No early stop —
        the whole loop runs on device; use generate() when stop tokens or
        streaming matter more than raw latency. With ``sampler`` given, the
        key chain starts from its seed — reproducible per request like
        ``generate``, but NOT bit-identical to it at temperature > 0: the
        fused loop consumes one chain key per CHUNK (splitting per step on
        device), while generate() splits the chain once per token.
        """
        scfg = sampler if sampler is not None else self.sampler_cfg
        temp, topp = jnp.float32(scfg.temperature), jnp.float32(scfg.topp)
        if sampler is not None:
            local_key = jax.random.PRNGKey(scfg.seed)

            def next_key():
                nonlocal local_key
                local_key, sub = jax.random.split(local_key)
                return sub
        else:
            next_key = self.next_key
        cache = self.new_cache()
        steps = min(steps, self.cfg.seq_len - len(prompt_tokens))
        t0 = time.perf_counter()
        if steps <= 0 and len(prompt_tokens) > 1:
            # nothing to emit; prefill still advances the session
            _, cache = self.prefill(cache, prompt_tokens, 0)
            self.prefill_ms = (time.perf_counter() - t0) * 1000.0
            self.final_session = Session(cache, len(prompt_tokens), pending_token=None)
            return [], self.prefill_ms, 0.0
        if len(prompt_tokens) > 1:
            last_logits, cache = self.prefill(cache, prompt_tokens, 0)
            token = sample_dynamic(last_logits, next_key(), temp, topp)
            pos = len(prompt_tokens)
            first = [int(token)]
            steps -= 1
        else:
            token = jnp.asarray(prompt_tokens[0], jnp.int32)
            pos = 0
            first = []
        token.block_until_ready()
        self.prefill_ms = prefill_ms = (time.perf_counter() - t0) * 1000.0
        if self._m_prefill is not None and len(prompt_tokens) > 1:
            self._m_prefill.observe(prefill_ms)

        # run the scan in BUCKETED chunk sizes so distinct `steps` values reuse
        # a handful of compiles (like prefill); overshooting the last chunk is
        # safe for the same reason tail-padded prefill is — discarded tokens
        # only touch cache slots a later decode overwrites before attending
        t1 = time.perf_counter()
        toks: list = []
        remaining = steps
        chunk_size = self.decode_chunk
        while remaining > 0:
            tc = time.perf_counter()
            # tail chunks reuse prefill buckets for compile sharing, but never
            # exceed the caller's chunk size (it bounds program size/latency);
            # prefill_bucket(r) >= r, so full chunks resolve to chunk_size
            n = min(chunk_size, prefill_bucket(remaining))
            n = min(n, self.cfg.seq_len - pos)  # never write cache out of range
            self._reduce_dispatch()  # solo chunks ride the row programs too
            chunk, cache, ok = self._decode_loop(
                cache, token, jnp.int32(pos), next_key(), temp, topp,
                self._poison_flag(), n_steps=n
            )
            take = min(n, remaining)
            if not bool(ok):
                if self._m_quarantine is not None:
                    self._m_quarantine.inc()
                raise NumericHealthError(
                    f"in fused decode chunk starting at position {pos}")
            chunk_list = [int(t) for t in np.asarray(chunk)]
            if self._m_chunk is not None:
                self._m_chunk.observe((time.perf_counter() - tc) * 1000.0)
            toks.extend(chunk_list[:take])
            token = chunk[-1]
            pos += take
            remaining -= take
        decode_ms = (time.perf_counter() - t1) * 1000.0

        emitted = first + toks
        if emitted:
            pending = emitted[-1]
        else:
            pending = prompt_tokens[0] if len(prompt_tokens) == 1 else None
        self.final_session = Session(cache, pos, pending_token=pending)
        return emitted, prefill_ms, decode_ms

    def generate_batch(
        self, prompts: list, steps: int,
        sampler: Optional[SamplerConfig] = None, stop_tokens: tuple = (),
        row_steps: Optional[list] = None,
        samplers: Optional[list] = None,
        on_chunk=None,
    ) -> list:
        """Decode B independent prompts TOGETHER: one weight-streaming pass
        per step serves every sequence (llama.forward_batched) — on
        bandwidth-bound decode that is ~B x the aggregate tokens/s of B
        sequential runs, a throughput mode the reference's batch=1 design
        has no analog for. Returns a list of B token lists; each row carries
        min(steps, its own remaining context) tokens — one near-full row
        never truncates the others (it pins at its last slot while the rest
        keep decoding). ``stop_tokens``: once EVERY row has emitted one (or
        reached its own budget) the remaining decode chunks are skipped —
        rows still carry tokens past their stop (the caller truncates, as
        the server batcher does); a short-reply batch doesn't pay the full
        step budget. ``row_steps``: per-row budgets for that done check
        (the server's mixed max_tokens; defaults to ``steps`` for all).

        Sampling: every row runs its OWN key chain, split once per step —
        the exact schedule ``generate`` walks. ``samplers`` gives row b its
        full per-request settings (temperature/topp/seed) — a sampled row
        is then BIT-IDENTICAL to a solo ``generate`` call with the same
        SamplerConfig (the server batches mixed concurrent requests on
        this; ``generate_fused`` differs at temperature > 0, see its
        docstring). With a single ``sampler``, rows share its
        temperature/topp and draw per-row chains split from its seed;
        greedy (temperature 0) rows are exact solo streams either way. With
        neither, the engine chain seeds the split.

        ``on_chunk(rows)``: called after every fused device chunk with the
        list of per-row tokens decoded so far THIS chunk (garbage past a
        row's own budget already trimmed) — the server's batched SSE
        streaming hook; tokens arrive in decode_chunk-sized bursts.

        Numeric health: ``self.row_health`` holds, after the call, one bool
        per row — False once the watchdog saw non-finite logits in that row
        (its tokens are garbage from that chunk on; siblings are unaffected).
        The caller decides the policy (the server maps False to
        ``finish_reason:"error"``); this fixed-membership path keeps
        decoding, unlike BatchSession's quarantine.
        """
        if not prompts or any(not p for p in prompts):
            raise ValueError("generate_batch needs non-empty prompts")
        B = len(prompts)
        if samplers is not None:
            if len(samplers) != B:
                raise ValueError(f"samplers must have {B} entries")
            temps = jnp.asarray([s.temperature for s in samplers], jnp.float32)
            topps = jnp.asarray([s.topp for s in samplers], jnp.float32)
            keys = jnp.stack([jax.random.PRNGKey(s.seed) for s in samplers])
        else:
            scfg = sampler if sampler is not None else self.sampler_cfg
            temps = jnp.full((B,), scfg.temperature, jnp.float32)
            topps = jnp.full((B,), scfg.topp, jnp.float32)
            base = (jax.random.PRNGKey(scfg.seed) if sampler is not None
                    else self.next_key())
            keys = jax.random.split(base, B)

        cache, pend, poss = self._prefill_batch_rows(prompts)
        tokens = jnp.asarray(pend, jnp.int32)
        pos = jnp.asarray(poss, jnp.int32)

        rooms = [self.cfg.seq_len - p for p in poss]  # feeds each row allows
        steps = min(steps, max(rooms))
        budgets = [
            min(rooms[b], row_steps[b] if row_steps else steps)
            for b in range(B)
        ]
        out: list = [[] for _ in range(B)]
        self.row_health = [True] * B
        if steps <= 0:
            self.decode_ms = 0.0
            return out
        remaining = steps
        t1 = time.perf_counter()
        while remaining > 0:
            tc = time.perf_counter()
            n = min(self.decode_chunk, prefill_bucket(remaining))
            chunk, cache, keys, ok = self.batch_loop(B)(
                cache, tokens, pos, keys, temps, topps,
                self._poison_rows(B), n_steps=n
            )
            take = min(n, remaining)
            arr = np.asarray(chunk)  # [n, B]
            okh = np.asarray(ok)  # [B]
            if self._m_chunk is not None:
                self._m_chunk.observe((time.perf_counter() - tc) * 1000.0)
            for b in range(B):
                if self.row_health[b] and not bool(okh[b]) \
                        and self._m_quarantine is not None:
                    self._m_quarantine.inc()
                self.row_health[b] = self.row_health[b] and bool(okh[b])
            done = steps - remaining  # tokens every row was offered so far
            fresh: list = [[] for _ in range(B)]
            for b in range(B):
                # a context-exhausted row pinned at its last slot: its tokens
                # past rooms[b] are garbage — keep only its own budget
                keep = max(0, min(take, rooms[b] - done))
                fresh[b] = [int(t) for t in arr[:keep, b]]
                out[b].extend(fresh[b])
            tokens = chunk[-1]
            # mirror the in-program per-row cap across chunk boundaries
            pos = jnp.minimum(pos + take, jnp.int32(self.cfg.seq_len - 1))
            remaining -= take
            if on_chunk is not None:
                on_chunk(fresh)
            if (stop_tokens or row_steps) and all(
                len(out[b]) >= budgets[b]
                or (stop_tokens and any(t in stop_tokens for t in out[b]))
                for b in range(B)
            ):
                break
        self.decode_ms = (time.perf_counter() - t1) * 1000.0
        return out

    def _prefill_batch_rows(self, prompts: list) -> tuple:
        """Shared-prefix batched prefill for the batch decode paths: init the
        [L, B, S, kv, hd] cache, prefill each DISTINCT prompt prefix once
        (rows sharing a prefix — the OpenAI `n` case — reuse it) and write
        it straight into the batch cache (donated in-place update), so peak
        HBM is the batch cache plus ONE single cache — never B side by
        side. The last prompt token stays pending (the uniform first
        batched step feeds it, so a row emits min(steps, room) tokens).
        Returns (cache, pending tokens [B], positions [B]); sets
        prefill_ms."""
        t0 = time.perf_counter()
        cache = self._batch_cache_init(len(prompts))
        groups: dict = {}
        for b, p in enumerate(prompts):
            if len(p) > 1:
                groups.setdefault(tuple(p[:-1]), []).append(b)
        for prefix, rows_b in groups.items():
            single = self.new_cache()
            _, single = self.prefill(single, list(prefix), 0)
            for b in rows_b:
                cache = self._batch_cache_insert(cache, single, jnp.int32(b))
            del single  # 1-token-prompt rows keep their zero slots
        pend = [int(p[-1]) for p in prompts]
        poss = [len(p) - 1 for p in prompts]
        self.prefill_ms = (time.perf_counter() - t0) * 1000.0
        if self._m_prefill is not None:
            self._m_prefill.observe(self.prefill_ms)
        return cache, pend, poss

    def batch_session(self, max_batch: int,
                      chunk: Optional[int] = None,
                      bucket_kv: bool = False,
                      min_bucket: Optional[int] = None,
                      prefill_chunk: int = 0,
                      kv_budget=None,
                      kv_pages: int = 0) -> "BatchSession":
        """Open a persistent slot-pool decode session (continuous batching):
        resident donated batch cache slabs whose rows are admitted, stepped,
        and released INDEPENDENTLY — see BatchSession.
        ``chunk`` is the fused steps per ``step_chunk`` call (defaults to the
        engine's decode_chunk). With ``bucket_kv=False`` (the default) the
        session is the classic single [L, max_batch, S, kv, hd] slab and
        (max_batch, chunk) picks the single _decode_loop_batch compile every
        chunk reuses; ``bucket_kv=True`` replaces it with power-of-two
        length-bucketed slot pools (from ``min_bucket`` up to seq_len) under
        the SAME modeled HBM budget of max_batch*seq_len KV token-slots, so
        short requests stop paying full-context HBM and strictly more rows
        fit. ``kv_pages`` > 0 goes further: TRUE PAGED KV — one arena of
        kv_pages-token pages under the same budget, per-row page tables, a
        radix prefix cache aliasing shared prompt pages copy-on-write, and
        zero migration copies (growing a row appends a page). 0 keeps the
        bucketed/uniform slab modes as the degenerate configurations.
        ``prefill_chunk`` > 0 sets the default token budget of
        prefill_step() for chunked (admit_begin) admissions. ``kv_budget``
        is an optional external accountant (serving.lifecycle.KVBudget) that
        mirrors reservations/occupancy into gauges (and, in paged mode,
        owns the page free list + refcounts via ``attach_pages``)."""
        return BatchSession(self, max_batch, chunk, bucket_kv=bucket_kv,
                            min_bucket=min_bucket, prefill_chunk=prefill_chunk,
                            kv_budget=kv_budget, kv_pages=kv_pages)

    def generate_batch_spec(
        self, prompts: list, steps: int,
        stop_tokens: tuple = (),
        row_steps: Optional[list] = None,
        draft_len: int = 8,
        ngram: int = 3,
        sampler: Optional[SamplerConfig] = None,
        on_step=None,
        row_cancel=None,
    ) -> tuple:
        """Batched GREEDY decode with prompt-lookup speculative drafting:
        every verify step scores draft_len+1 candidate positions for ALL B
        sequences in one weight-streaming pass — the two bandwidth
        multipliers (batching across sequences, speculation across
        positions) composed. Beyond both the reference (one token, one
        sequence per step) and this engine's own generate_batch /
        generate_spec taken alone.

        Returns (rows, stats): row b equals generate_batch's greedy row b
        truncated at its first stop token (speculation changes the
        schedule, never the tokens — per-position argmax is what the plain
        batched step computes; generate_batch rows may CARRY tokens past a
        stop for the caller to truncate, this path truncates itself);
        stats = {"verify_steps", "accepted_drafts", "emitted"}.

        Greedy only (``sampler`` with temperature > 0 raises): replaying B
        per-row sampled key chains through a shared-T verify is bookkeeping
        this path doesn't carry yet — sampled batches run generate_batch,
        sampled solo spec runs generate_spec. Runs single-device AND under
        quantized TP (the shard_map verify wrapper,
        parallel.quant_tp.make_tp_verify_batched); only the dense-pjit
        mesh path raises (supports_batch_spec). Rows with no matching
        n-gram still verify their pending token (a T-row step emits at
        least 1 token per row, exactly like plain decode).

        ``on_step(fresh)``: called after every verify launch with each
        row's tokens emitted by THAT launch (empty for finished rows) —
        the server's batched-spec SSE hook. Unlike generate_batch's
        on_chunk, bursts here are final (budget- and stop-truncated
        already) and arrive every 1..draft_len+1 tokens.

        ``row_cancel(b) -> bool``: re-checked for every unfinished row
        between verify launches; True marks the row done on the spot — a
        cancelled/expired request stops consuming verify work at the next
        launch boundary instead of riding to batch end (the row then
        re-verifies its pending token in place like any finished row, which
        is how speculation's fixed row set is preserved). Its emissions up
        to the cancellation stand.

        Cache safety mirrors generate_spec: rejected/pad slots hold garbage
        K/V that later steps overwrite before any query attends them; a
        FINISHED row keeps verifying its pending token in place without
        advancing — its emissions are already taken, and its (per-row) cache
        slab can't affect other rows.
        """
        if not prompts or any(not p for p in prompts):
            raise ValueError("generate_batch_spec needs non-empty prompts")
        if not self.supports_batch_spec:
            raise ValueError(
                "generate_batch_spec does not run on the dense-pjit mesh "
                "path (no shard_map wrapper for the batched verify "
                "forward); quantized-TP and single-device engines support "
                "it — use generate_batch here")
        scfg = sampler if sampler is not None else self.sampler_cfg
        if scfg.temperature > 0.0:
            raise ValueError(
                "generate_batch_spec is greedy-only; use generate_batch for "
                "sampled batches or generate_spec for sampled solo decoding")
        B = len(prompts)
        S = self.cfg.seq_len
        if sampler is None:
            # mirror generate_batch's no-sampler branch, which burns one
            # engine-chain key even when greedy — substituting this path
            # must not desync later sampled calls on the same engine chain
            self.next_key()

        cache, pend, poss = self._prefill_batch_rows(prompts)

        rooms = [S - p for p in poss]
        budgets = [min(rooms[b], row_steps[b] if row_steps else steps,
                       steps) for b in range(B)]
        indexes = [_NgramIndex(ngram) for _ in range(B)]
        for b, p in enumerate(prompts):
            indexes[b].extend(p[:-1])
        out: list = [[] for _ in range(B)]
        done = [budgets[b] <= 0 for b in range(B)]
        verify_steps = accepted = 0

        t1 = time.perf_counter()
        while not all(done):
            if row_cancel is not None:
                for b in range(B):
                    if not done[b] and row_cancel(b):
                        done[b] = True
                if all(done):
                    break
            # shared static T, shrunk so the most context-constrained ACTIVE
            # row's write window stays in range (T values bucket to at most
            # draft_len+1 distinct compiles)
            T = min(draft_len + 1,
                    min(S - poss[b] for b in range(B) if not done[b]))
            T = max(T, 1)
            feeds, drafts = [], []
            for b in range(B):
                if done[b]:
                    drafts.append([])
                    feeds.append([pend[b]] * T)  # re-verify in place
                    continue
                k = min(T - 1, budgets[b] - len(out[b]) - 1)
                d = indexes[b].draft(pend[b], k) if k > 0 else []
                drafts.append(d)
                feeds.append([pend[b]] + d + [0] * (T - 1 - len(d)))
            g, cache = self.verify_program(B)(
                cache, jnp.asarray(feeds, jnp.int32),
                jnp.asarray([min(poss[b], S - T) if done[b] else poss[b]
                             for b in range(B)], jnp.int32))
            g = np.asarray(g)  # [B, T]
            verify_steps += 1
            fresh: list = [[] for _ in range(B)]
            for b in range(B):
                if done[b]:
                    continue
                row = [int(v) for v in g[b]]
                m = 0
                while m < len(drafts[b]) and drafts[b][m] == row[m]:
                    m += 1
                accepted += m
                emit = row[: m + 1]
                take = min(len(emit), budgets[b] - len(out[b]))
                for j in range(take):
                    if emit[j] in stop_tokens:
                        take = j + 1
                        break
                emit = emit[:take]
                indexes[b].extend([pend[b]] + drafts[b][:m])
                out[b].extend(emit)
                fresh[b] = emit
                pend[b] = emit[-1]
                poss[b] += m + 1
                if (len(out[b]) >= budgets[b]
                        or (stop_tokens and emit
                            and emit[-1] in stop_tokens)):
                    done[b] = True
            if on_step is not None:
                on_step(fresh)
        self.decode_ms = (time.perf_counter() - t1) * 1000.0
        emitted_total = sum(len(r) for r in out)
        if self._m_spec_steps is not None:
            self._m_spec_steps.inc(verify_steps)
            self._m_spec_accepted.inc(accepted)
            self._m_spec_emitted.inc(emitted_total)
        return out, {"verify_steps": verify_steps,
                     "accepted_drafts": accepted,
                     "emitted": emitted_total}

    def generate_spec(
        self,
        prompt_tokens: list,
        steps: int,
        session: Optional[Session] = None,
        stop_tokens: tuple = (),
        draft_len: int = 8,
        ngram: int = 3,
        history: Optional[list] = None,
        sampler: Optional[SamplerConfig] = None,
    ) -> Iterator[tuple]:
        """Decoding with prompt-lookup speculative drafting — greedy or
        sampled, both EXACT.

        Drafts the next ``draft_len`` tokens by matching the trailing
        ``ngram`` of the context against its own history (the continuation
        that followed the same n-gram last time), then scores pending +
        draft in ONE verify step and accepts the longest matching prefix —
        m matched drafts emit m+1 tokens for one weight-streaming pass, a
        pure win on bandwidth-bound decode whenever text repeats (quoting,
        code, structured output). Beyond the reference's capabilities
        (single token per step, `src/tasks.cpp:199-210`).

        Exactness: at temperature 0 the verify compares against per-position
        argmax. At temperature > 0 it compares against the token sequential
        decoding would have SAMPLED — the verify step evaluates position i
        with the i-th key of the same per-token key chain ``generate`` walks
        (``sampler`` given: a fresh chain from its seed, as in generate;
        otherwise the engine chain) — so the emitted stream is identical to
        plain decode with the same sampler, batch boundaries and all.
        Acceptance just happens less often as temperature rises. The chain
        advances exactly once per EMITTED token — at temperature 0 too
        (plain generate() burns one key per token via next_key() even when
        greedy ignores it, so the greedy path here must consume identically
        or a later sampled call on the same engine chain would diverge) —
        and a stop token or the steps cap truncating a batch truncates the
        advancement with it, keeping later turns on the engine chain
        aligned with plain decode.

        Cache safety on rejection needs no rollback: rejected draft slots
        hold garbage K/V, but every future step writes position p before any
        query attends it — the same overwrite-before-attend invariant as
        tail-padded prefill.

        ``history``: tokens already consumed into the session's cache before
        this call (exclusive of its pending token) — resuming callers (e.g.
        the API server's prefix cache) pass the prior conversation so the
        n-gram lookup can draft from earlier turns, which is where the
        repetition lives. Draft quality only; output is exact regardless.
        """
        scfg = sampler if sampler is not None else self.sampler_cfg
        temp, topp = jnp.float32(scfg.temperature), jnp.float32(scfg.topp)
        sampled = scfg.temperature > 0.0
        chain = jax.random.PRNGKey(scfg.seed) if sampler is not None else self._key

        def peek(n):
            """n per-token keys + the chain state after each — the caller
            commits to a prefix of them via commit(states[i])."""
            c, subs, states = chain, [], []
            for _ in range(n):
                c, sub = jax.random.split(c)
                subs.append(sub)
                states.append(c)
            return subs, states

        def commit(state):
            nonlocal chain
            chain = state
            if sampler is None:
                self._key = chain  # mirror next_key()'s engine-chain use

        if session is None:
            cache, pos = self.new_cache(), 0
        else:
            cache, pos = session.cache, session.pos
            if session.pending_token is not None:
                prompt_tokens = [session.pending_token] + list(prompt_tokens)
        if not prompt_tokens:
            raise ValueError(
                "generate_spec needs at least one token to feed — an empty "
                "prompt requires a session with a pending_token"
            )
        steps = min(steps, self.cfg.seq_len - pos - len(prompt_tokens))

        t0 = time.perf_counter()
        # the index covers tokens already consumed into the cache; the
        # pending `token` joins it only when a verify step consumes it
        index = _NgramIndex(ngram)
        if history:
            index.extend(history)
        if len(prompt_tokens) > 1:
            index.extend(prompt_tokens)
            last_logits, cache = self.prefill(cache, prompt_tokens, pos)
            subs, states = peek(1)
            commit(states[0])
            if sampled:
                token = int(sample_dynamic(last_logits, subs[0], temp, topp))
            else:
                token = int(jnp.argmax(last_logits))
            pos += len(prompt_tokens)
        else:
            token = int(prompt_tokens[0])
        self.prefill_ms = (time.perf_counter() - t0) * 1000.0

        if steps <= 0:
            # token is the pending next input in both branches above
            self.final_session = Session(cache, pos, pending_token=token)
            return

        emitted = 0
        first = len(prompt_tokens) > 1
        while emitted < steps:
            t1 = time.perf_counter()
            from_prefill = first
            if first:
                # the prefill already produced one token "for free"; the
                # prompt is consumed, so per-token pos below starts at pos-1.
                # Its stats report the prefill cost (like generate()'s first
                # token) — the loop did no work for it
                out, first, base = [token], False, pos - 1
                batch_rows = self._last_prefill_bucket
            else:
                # fixed feed length -> ONE verify compile for the whole run;
                # pad slots write garbage K/V at pos+m+1.. which every later
                # step overwrites before attending (see docstring). Only the
                # sequence tail shrinks the feed (at most one extra compile
                # per distinct tail length).
                L = min(draft_len + 1, self.cfg.seq_len - pos)
                k = min(L - 1, steps - emitted - 1)  # >= 0: emitted < steps
                draft = index.draft(token, k)
                feed = jnp.asarray(
                    [token] + draft + [0] * (L - 1 - len(draft)), jnp.int32)
                subs, states = peek(L)
                if sampled:
                    g, cache = self._verify_sampled(
                        cache, feed, jnp.int32(pos), jnp.stack(subs), temp, topp)
                else:
                    g, cache = self._verify_step(cache, feed, jnp.int32(pos))
                g = [int(v) for v in np.asarray(g)]
                # accept drafts while they match the model's own (greedy or
                # key-chain-sampled) choice
                m = 0
                while m < len(draft) and draft[m] == g[m]:
                    m += 1
                out = g[: m + 1]  # m matched drafts + the correcting token
                # how many of them will actually be EMITTED (steps cap, stop
                # tokens) — the key chain must advance by exactly that many,
                # or later turns on the engine chain diverge from plain decode
                take = min(len(out), steps - emitted)
                for j in range(take):
                    if out[j] in stop_tokens:
                        take = j + 1
                        break
                out = out[:take]
                commit(states[take - 1])
                if self._m_spec_steps is not None:
                    self._m_spec_steps.inc()
                    self._m_spec_accepted.inc(m)
                    self._m_spec_emitted.inc(take)
                index.extend([token] + draft[:m])
                # (on a truncated batch the generator is about to return /
                # exit, so the pending token is never fed again)
                token = out[-1]
                base = pos  # position before this batch's tokens
                pos += m + 1
                batch_rows = L
            dt = self.prefill_ms if from_prefill else (time.perf_counter() - t1) * 1000.0
            # this batch's collectives gathered batch_rows rows, not one
            # (cf. the prefill row's accounting in generate())
            batch_kb = self.wire_kb(batch_rows)
            for i, tk in enumerate(out):
                emitted += 1
                # per-token session pos: a consumer stopping at token i must
                # resume as if only tokens 0..i were ever consumed — slots
                # written beyond are overwritten before any resume attends
                self.final_session = Session(cache, base + i + 1, pending_token=tk)
                yield tk, TokenStats(
                    generation_ms=dt if i == 0 else 0.0,
                    inference_ms=dt if i == 0 else 0.0,
                    sent_kb=batch_kb if i == 0 else 0.0,
                    recv_kb=batch_kb if i == 0 else 0.0,
                )
                if tk in stop_tokens:
                    return
        # final_session is already exact: the last yield recorded (cache,
        # pos-of-that-token, pending) — tokens speculated past the `steps`
        # cap were never emitted and their cache slots will be overwritten
        # before any resumed decode attends them


@dataclasses.dataclass
class _SlotState:
    """Host-side bookkeeping for one admitted BatchSession row."""

    room: int  # feeds the row's remaining context allows (S - admit pos)
    budget: int  # min(room, the caller's step budget)
    stop_tokens: tuple
    reserved: int  # KV token-slots reserved against the session budget
    offered: int = 0  # tokens the fused chunks have offered this row so far
    done: bool = False  # budget/stop reached; pinned in place until release()
    emitted: int = 0  # tokens actually kept (post budget/stop truncation)
    finish: Optional[str] = None  # "stop" | "length" | "error" once done
    prefilling: bool = False  # admit_begin()ed, prompt not fully consumed
    prefill_ms: float = 0.0  # accumulated admission-prefill wall time


class _PendingPrefill:
    """A chunked admission's in-flight prompt state (admit_begin)."""

    __slots__ = ("prompt", "scfg", "cache", "cursor", "pub_nodes",
                 "scattered")

    def __init__(self, prompt: list, scfg: SamplerConfig, cache: dict):
        self.prompt = prompt
        self.scfg = scfg
        self.cache = cache  # single-sequence [L, S, kv, hd] being filled
        self.cursor = 0  # prompt-prefix tokens already prefilled
        # paged publish-at-admit state: the radix nodes this admission
        # created ready=False (index-aligned with the row's blocks; None
        # where another row's node already existed), and the token count
        # already scattered from the staging cache into arena pages
        self.pub_nodes: list = []
        self.scattered = 0


class _BucketPool:
    """One context bucket's slot pool: a [L, cap, ctx, kv, hd] donated slab
    plus host-side per-row decode state (numpy mirrors, shipped to the
    device per fused chunk). ``ctx`` may be shorter than the model context:
    attention masks by ``pos`` and clamps writes to the slab, so a short
    slab is exact as long as every live row's position stays inside it —
    the session migrates rows out before they outgrow it."""

    __slots__ = ("ctx", "cap", "cache", "tokens", "pos", "keys", "temps",
                 "topps", "rows")

    def __init__(self, eng: Engine, ctx: int, cap: int):
        self.ctx = ctx
        self.cap = cap
        self.cache = eng._bucket_cache_init(cap, ctx)
        self.tokens = np.zeros((cap,), np.int32)
        # free rows pin at the slab's last slot, like exhausted rows
        self.pos = np.full((cap,), ctx - 1, np.int32)
        self.keys = np.zeros((cap, 2), np.uint32)
        self.temps = np.zeros((cap,), np.float32)
        self.topps = np.ones((cap,), np.float32)
        self.rows: list = [None] * cap  # handle occupying each row

    def grow(self, eng: Engine) -> None:
        """Double the pool's capacity in place: rows keep their indices (no
        handle in the session moves), the new tail rows start free/pinned.
        Doubling bounds the retraces of the pool's decode program to
        log2(rows) for the whole session."""
        new_cap = self.cap * 2
        bigger = eng._bucket_cache_init(new_cap, self.ctx)
        self.cache = eng._bucket_cache_grow(bigger, self.cache)
        pad = new_cap - self.cap
        self.tokens = np.concatenate(
            [self.tokens, np.zeros((pad,), np.int32)])
        self.pos = np.concatenate(
            [self.pos, np.full((pad,), self.ctx - 1, np.int32)])
        self.keys = np.concatenate(
            [self.keys, np.zeros((pad, 2), np.uint32)])
        self.temps = np.concatenate(
            [self.temps, np.zeros((pad,), np.float32)])
        self.topps = np.concatenate(
            [self.topps, np.ones((pad,), np.float32)])
        self.rows.extend([None] * pad)
        self.cap = new_cap


class _RowPages:
    """One paged row's page-table state: ``blocks[b]`` is the physical
    arena page holding logical token block b (aliased prefix pages first,
    private tail pages appended as the row grows). ``outstanding`` is the
    row's reserved-but-unallocated private page count (returned to the
    allocator at release); ``cap_tokens`` its worst-case context
    (admission's _need_ctx), the hard bound page appends never exceed."""

    __slots__ = ("blocks", "outstanding", "cap_tokens", "plen")

    def __init__(self, blocks: list, outstanding: int, cap_tokens: int,
                 plen: int):
        self.blocks = blocks
        self.outstanding = outstanding
        self.cap_tokens = cap_tokens
        self.plen = plen


class _PagedGroup:
    """Host-side row state for one paged decode shape: every row whose page
    table currently spans ``nb`` blocks shares one compiled decode program
    (window = nb*page tokens). Unlike _BucketPool there is NO device cache
    here — KV lives in the session-wide arena — so moving a growing row to
    a wider group is a host-side table rewrite, never a device copy: the
    bucket-migration copy is gone by construction. Free rows pin at the
    window's last slot with an all-scratch table (their writes land on the
    garbage page)."""

    __slots__ = ("nb", "cap", "tables", "tokens", "pos", "keys", "temps",
                 "topps", "rows")

    def __init__(self, nb: int, cap: int, page: int):
        self.nb = nb
        self.cap = cap
        self.tables = np.full((cap, nb), paged_kv.SCRATCH_PAGE, np.int32)
        self.tokens = np.zeros((cap,), np.int32)
        self.pos = np.full((cap,), nb * page - 1, np.int32)
        self.keys = np.zeros((cap, 2), np.uint32)
        self.temps = np.zeros((cap,), np.float32)
        self.topps = np.ones((cap,), np.float32)
        self.rows: list = [None] * cap

    def grow(self, page: int) -> None:
        """Double capacity in place (host arrays only; compile count per
        group stays log2(rows) like _BucketPool.grow)."""
        pad = self.cap
        self.tables = np.concatenate(
            [self.tables,
             np.full((pad, self.nb), paged_kv.SCRATCH_PAGE, np.int32)])
        self.tokens = np.concatenate(
            [self.tokens, np.zeros((pad,), np.int32)])
        self.pos = np.concatenate(
            [self.pos, np.full((pad,), self.nb * page - 1, np.int32)])
        self.keys = np.concatenate(
            [self.keys, np.zeros((pad, 2), np.uint32)])
        self.temps = np.concatenate(
            [self.temps, np.zeros((pad,), np.float32)])
        self.topps = np.concatenate(
            [self.topps, np.ones((pad,), np.float32)])
        self.rows.extend([None] * pad)
        self.cap *= 2


class BatchSession:
    """Slot-pool decode over resident donated batch cache slabs — the
    continuous-batching primitive. Where ``generate_batch`` forms a batch
    once and runs it to completion (a long row holds the device while short
    rows' slots idle), a BatchSession lets rows join (``admit`` /
    ``admit_begin``), step (``step_chunk``), and leave (``release``)
    independently BETWEEN fused decode chunks: the serving scheduler admits
    newly arrived requests into freed capacity while their neighbours keep
    decoding.

    Row math is EXACTLY generate_batch's: every chunk is one
    ``_decode_loop_batch`` program per occupied pool, each row running its
    OWN sampler chain (key split once per step) — so a row admitted
    mid-flight emits a stream BIT-IDENTICAL to a solo ``generate`` call
    with the same SamplerConfig, no matter what its neighbours are doing.
    Free/finished rows ride along pinned in place (pos clamped at the
    slab's last slot, feeding token 0) exactly like context-exhausted rows
    in generate_batch: their writes are garbage at slots no live query
    attends.

    Two residency layouts share this class. ``bucket_kv=False`` (default)
    is the classic single [L, max_batch, S, kv, hd] slab: handles ARE slot
    indices 0..max_batch-1 and one compile serves the whole session.
    ``bucket_kv=True`` shards residency into power-of-two context buckets
    under the SAME modeled HBM budget (max_batch * seq_len KV token-slots):
    a row is admitted into the smallest slab covering its prompt plus one
    decode chunk, reserves its worst-case bucket (prompt+steps) against the
    budget, and MIGRATES to the next bucket just before outgrowing its
    slab — so short requests stop paying full-context HBM and strictly
    more rows fit at fixed memory. One decode program per occupied
    (bucket, capacity) shape; capacities double, bounding retraces.

    Slot-slab reuse needs no clearing: admitting a multi-token prompt
    overwrites the slot's whole attended window (_batch_cache_insert), and
    a 1-token prompt starts at pos 0 where overwrite-before-attend holds —
    every position <= pos is written by the CURRENT occupant before any of
    its queries attends it; stale garbage sits only at masked positions.
    Migration copies the row's whole slab, i.e. its entire attended
    history, so the invariant carries across buckets.
    """

    def __init__(self, eng: Engine, max_batch: int, chunk: Optional[int] = None,
                 bucket_kv: bool = False, min_bucket: Optional[int] = None,
                 prefill_chunk: int = 0, kv_budget=None, kv_pages: int = 0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        chunk = eng.decode_chunk if chunk is None else chunk
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.eng = eng
        self.max_batch = max_batch
        self.chunk = chunk
        self.paged = kv_pages > 0
        self.bucket_kv = bool(bucket_kv) and not self.paged
        self.prefill_chunk = max(0, int(prefill_chunk))
        S = eng.cfg.seq_len
        if self.paged:
            # page size must divide the model context so logical blocks tile
            # it exactly (a partial tail block would misplace the staging
            # copies); halve the requested size until it does
            page = max(1, int(kv_pages))
            while S % page:
                page //= 2
            self.page = page
        if self.bucket_kv:
            # the bucket ladder: powers of two from min_bucket (default: a
            # couple of decode chunks — smaller slabs would migrate every
            # other chunk) up to the full model context
            lo = int(min_bucket) if min_bucket else max(16, 2 * chunk)
            lo = max(2, min(lo, S))
            b = 1
            while b < lo:
                b *= 2
            ladder = []
            while b < S:
                ladder.append(b)
                b *= 2
            ladder.append(S)
            self.buckets = tuple(ladder)
        else:
            self.buckets = (S,)
        #: modeled HBM budget in KV token-slots — what the uniform slab
        #: spends as max_batch full-context rows; bucketed admission packs
        #: strictly more short rows into the same budget
        self.budget_tokens = max_batch * S
        self._reserved_tokens = 0
        self._budget = kv_budget  # duck-typed lifecycle.KVBudget mirror
        self._pools: dict = {}  # ctx -> _BucketPool
        self._slots: dict = {}  # handle -> _SlotState
        self._where: dict = {}  # handle -> (pool, row)
        self._prefills: dict = {}  # handle -> _PendingPrefill (FIFO)
        self._next_handle = 0
        self._closed = False
        self.migrations = 0  # rows moved to a larger bucket, this session
        self.decode_ms = 0.0  # cumulative fused-chunk wall time
        self.prefill_ms = 0.0  # cumulative admit-prefill wall time
        # paged-mode telemetry (all stay 0 in slab modes)
        self.prefix_hits = 0  # admits that aliased >= 1 cached page
        self.prefix_misses = 0  # admits with nothing cached to alias
        self.prefix_tokens_matched = 0  # prompt tokens served from cache
        self.cow_copies = 0  # boundary pages privately copied at admit
        self.prefix_evictions = 0  # cached pages LRU-evicted for allocs
        self.regroups = 0  # host-side table moves (the ex-migrations)
        if self.paged:
            # ONE preallocated arena under the same modeled HBM budget the
            # uniform slab spends (+1 scratch page): [L, P, page, kv, hd]
            num_pages = self.budget_tokens // self.page + 1
            self._arena = eng._bucket_cache_init(num_pages, self.page)
            if kv_budget is not None and hasattr(kv_budget, "attach_pages"):
                # the serving accountant owns the free list + refcounts
                # (and publishes them as gauges); the session drives it
                self._alloc = kv_budget.attach_pages(num_pages, self.page)
            else:
                self._alloc = paged_kv.PageAllocator(num_pages, self.page)
            self._radix = paged_kv.RadixPrefixCache(self.page)
            self._pgroups: dict = {}  # nb -> _PagedGroup
            self._rowpages: dict = {}  # handle -> _RowPages
            max_nb = S // self.page
            ladder, nb = [], 1
            while nb < max_nb:
                ladder.append(nb)
                nb *= 2
            ladder.append(max_nb)
            self._nb_ladder = tuple(ladder)
        elif not self.bucket_kv:
            # the classic resident slab, pre-allocated so the pool never
            # grows and handles stay the historical slot indices 0..B-1
            self._pools[S] = _BucketPool(eng, S, max_batch)

    # -- introspection ----------------------------------------------------
    @property
    def cache(self):
        """The uniform-mode resident slab. Bucketed sessions keep one slab
        per occupied bucket, paged sessions one page arena; neither has a
        single per-session cache to point at."""
        if self._closed or self.bucket_kv or self.paged:
            return None
        return self._pools[self.eng.cfg.seq_len].cache

    @property
    def free_slots(self) -> list:
        """Row indices admit() can take right now (uniform mode: the actual
        free slot indices, the historical contract). Bucketed/paged
        sessions admit by KV budget, not row count — prefer ``can_admit``;
        here the number of smallest admissions (one bucket / one page) that
        still fit is returned as pseudo-indices so ``if sess.free_slots:``
        keeps meaning "can admit something"."""
        if self.paged:
            n = (self._alloc.free_count + self._alloc.evictable_count
                 - self._alloc.reserved_pages)
            return list(range(max(0, n)))
        if not self.bucket_kv:
            pool = self._pools[self.eng.cfg.seq_len]
            return [b for b, h in enumerate(pool.rows) if h is None]
        n = (self.budget_tokens - self._reserved_tokens) // self.buckets[0]
        return list(range(max(0, n)))

    @property
    def occupied(self) -> list:
        """Admitted-and-not-released handles (done + mid-prefill included)."""
        return sorted(self._slots)

    @property
    def num_live(self) -> int:
        """Rows the next step_chunk will actually advance."""
        return sum(1 for st in self._slots.values()
                   if not st.done and not st.prefilling)

    @property
    def pending_prefills(self) -> list:
        """Handles admitted via admit_begin whose prompts are still being
        consumed, oldest first."""
        return list(self._prefills)

    @property
    def reserved_tokens(self) -> int:
        """KV token-slots currently reserved against ``budget_tokens``."""
        return self._reserved_tokens

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of paged admits that aliased >= 1 cached page (0.0 in
        slab modes and before any admission)."""
        n = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / n if n else 0.0

    def page_stats(self) -> dict:
        """Paged-mode occupancy snapshot for /stats and /ready ({} in slab
        modes): allocator page counts, radix-tree size, per-window resident
        rows, and the prefix-cache counters."""
        if not self.paged:
            return {}
        s = self._alloc.stats()
        s["radix_nodes"] = len(self._radix)
        s["rows_per_window"] = {
            str(nb * self.page): sum(1 for h in g.rows if h is not None)
            for nb, g in sorted(self._pgroups.items())}
        s["prefix_hits"] = self.prefix_hits
        s["prefix_misses"] = self.prefix_misses
        s["prefix_hit_rate"] = self.prefix_hit_rate
        s["prefix_tokens_matched"] = self.prefix_tokens_matched
        s["cow_copies"] = self.cow_copies
        s["prefix_evictions"] = self.prefix_evictions
        s["regroups"] = self.regroups
        return s

    def _state(self, slot: int) -> _SlotState:
        st = self._slots.get(slot)
        if st is None:
            raise ValueError(f"slot {slot} is not occupied")
        return st

    def is_done(self, slot: int) -> bool:
        """True once the row hit its stop token, budget, or quarantine (it no
        longer receives tokens; release() it to free the slab)."""
        return self._state(slot).done

    def finish_reason(self, slot: int) -> Optional[str]:
        """Why the row finished: ``"stop"``, ``"length"``, ``"error"``
        (watchdog quarantine), or None while still live / after cancel()."""
        return self._state(slot).finish

    def prefill_ms_of(self, slot: int) -> float:
        """Wall time this row's admission prefill has consumed so far."""
        return self._state(slot).prefill_ms

    # -- capacity ---------------------------------------------------------
    def _need_ctx(self, prompt_len: int, steps: int) -> int:
        """Context slots the row can reach: its final write position + 1."""
        S = self.eng.cfg.seq_len
        return max(prompt_len, min(S, prompt_len - 1 + max(0, steps)))

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def can_admit(self, prompt_len: int, steps: int,
                  prompt_tokens: Optional[list] = None) -> bool:
        """True when the session's modeled KV budget (and the external
        kv_budget, if any) has room for this request's WORST-CASE need —
        admission reserves the bucket (or private page count) covering
        prompt+steps up front so later growth can never oversubscribe.
        Paged sessions reserve only the pages the radix prefix cache can't
        alias; pass ``prompt_tokens`` to let this check count the match
        (without it the answer is conservative: zero match assumed)."""
        if self._closed:
            return False
        if self.paged:
            priv, full, _ = self._plan_pages(prompt_len, steps,
                                             prompt_tokens)
            # matched evictable pages would be pinned by this admit, leaving
            # the availability pool — count them alongside the private need
            pinned = sum(1 for n in full
                         if self._alloc.refcount(n.page) == 0)
            if not self._alloc.can_reserve(priv + pinned):
                return False
            if self._budget is not None and not self._budget.can_fit(
                    priv * self.page):
                return False
            return True
        need = self._bucket_for(self._need_ctx(prompt_len, steps))
        if self._reserved_tokens + need > self.budget_tokens:
            return False
        if self._budget is not None and not self._budget.can_fit(need):
            return False
        return True

    # -- paged-mode internals ---------------------------------------------
    def _plan_pages(self, prompt_len: int, steps: int,
                    prompt_tokens: Optional[list]) -> tuple:
        """(private pages to reserve, aliasable full-prefix nodes, COW
        boundary node) for a prospective paged admission. ``full`` nodes
        cache blocks strictly below position prompt_len-1 (never written by
        this row — safe to alias); the COW node, when the prompt ends flush
        on the next cached block, is copied privately instead (its last
        slot is the pending token's write target)."""
        need = paged_kv.pages_for(
            self._need_ctx(prompt_len, steps), self.page)
        if prompt_tokens is None:
            return need, [], None
        path = self._radix.match(prompt_tokens)
        nfull = min(len(path), (prompt_len - 1) // self.page)
        full = path[:nfull]
        cow = None
        if len(path) > nfull and (nfull + 1) * self.page == prompt_len:
            cow = path[nfull]
        return need - nfull, full, cow

    def _page_alloc(self, rp: _RowPages) -> int:
        """One private arena page for ``rp``'s row, evicting LRU prefix-
        cache pages if the free list is dry — guaranteed to succeed for a
        reserved row (admission counted free + evictable)."""
        faults.fire("page_alloc")
        p = self._alloc.alloc()
        if p is None:
            freed = self._radix.evict(1, self._alloc)
            self.prefix_evictions += freed
            if self.eng._m_prefix_evictions is not None and freed:
                self.eng._m_prefix_evictions.inc(freed)
            p = self._alloc.alloc()
        if p is None:
            raise RuntimeError(
                "paged KV pool exhausted despite admission reservation — "
                "page accounting bug")
        rp.outstanding = max(0, rp.outstanding - 1)
        return p

    def _nb_for(self, blocks: int) -> int:
        for nb in self._nb_ladder:
            if nb >= blocks:
                return nb
        return self._nb_ladder[-1]

    def _pad_pages(self, pages: list, offs: Optional[list] = None):
        """Scratch-pad a page (and optional offset) list to the next power
        of two so the batched admit copies (Engine._pages_to_single /
        _single_to_pages) compile one program per size bucket instead of
        one per distinct prefix length. Padded entries resolve to the
        scratch page — garbage writes/reads the copy helpers mask or the
        arena convention already tolerates."""
        n = max(1, len(pages))
        m = 1
        while m < n:
            m *= 2
        pad = m - len(pages)
        out = jnp.asarray(pages + [paged_kv.SCRATCH_PAGE] * pad, jnp.int32)
        if offs is None:
            return out
        return out, jnp.asarray(offs + [0] * pad, jnp.int32)

    def _alloc_prow(self, nb: int) -> tuple:
        """A free row in the ``nb``-block group, materializing/growing it
        on demand (mirrors _alloc_row)."""
        g = self._pgroups.get(nb)
        if g is None:
            g = self._pgroups[nb] = _PagedGroup(nb, 1, self.page)
        for r in range(g.cap):
            if g.rows[r] is None:
                return g, r
        r = g.cap
        g.grow(self.page)
        return g, r

    def _sync_table(self, handle: int) -> None:
        """Mirror the row's logical block list into its group's device-
        bound page table (scratch-padded past the allocated tail)."""
        g, r = self._where[handle]
        rp = self._rowpages[handle]
        g.tables[r, :] = paged_kv.SCRATCH_PAGE
        n = min(len(rp.blocks), g.nb)
        g.tables[r, :n] = rp.blocks[:n]

    def _regroup(self, handle: int, nb: int) -> None:
        """Move a growing row to a wider window group. Pure host-side
        state: the KV never moves (it lives in arena pages) — this is what
        killed the bucket-migration copy."""
        src, srow = self._where[handle]
        dst, drow = self._alloc_prow(nb)
        dst.tokens[drow] = src.tokens[srow]
        dst.pos[drow] = src.pos[srow]
        dst.keys[drow] = src.keys[srow]
        dst.temps[drow] = src.temps[srow]
        dst.topps[drow] = src.topps[srow]
        dst.rows[drow] = handle
        src.rows[srow] = None
        src.pos[srow] = src.nb * self.page - 1
        src.tables[srow, :] = paged_kv.SCRATCH_PAGE
        self._where[handle] = (dst, drow)
        self.regroups += 1
        self._sync_table(handle)

    def _finish_pages(self, handle: int, prompt_tokens: list,
                      staging: Optional[dict] = None) -> None:
        """Complete a paged row's table through its pending-token block:
        allocate the private tail pages, scatter the staging cache's
        prefilled blocks into them (``staging`` None on the no-prefill
        paths — fully cached or 1-token prompts, whose block contents are
        either aliased/COW-copied already or written by the first decode
        step before anything attends them), then publish every fully-
        prompt-covered block into the radix tree."""
        rp = self._rowpages[handle]
        plen = len(prompt_tokens)
        total = (plen - 1) // self.page + 1
        # allocation stays a host loop (per-page fault seam + allocator
        # bookkeeping); the device scatters coalesce into ONE dispatch below
        scat_pages: list = []
        scat_offs: list = []
        for b in range(len(rp.blocks), total):
            p = self._page_alloc(rp)
            if staging is not None and b * self.page < plen - 1:
                scat_pages.append(p)
                scat_offs.append(b * self.page)
            rp.blocks.append(p)
        if scat_pages:
            pages, offs = self._pad_pages(scat_pages, scat_offs)
            self._arena = self.eng._single_to_pages(
                self._arena, staging, pages, offs)
        # blocks with (b+1)*page <= plen-1 hold immutable prompt KV (this
        # row only writes at pos >= plen-1): cacheable for future admits
        nins = (plen - 1) // self.page
        for p in self._radix.insert(prompt_tokens, rp.blocks[:nins]):
            self._alloc.hold(p)
        self._sync_table(handle)

    def _alloc_row(self, ctx: int) -> tuple:
        """A free row in the ``ctx`` pool, materializing/growing it on
        demand (bucketed mode; the uniform pool is pre-sized)."""
        pool = self._pools.get(ctx)
        if pool is None:
            pool = self._pools[ctx] = _BucketPool(self.eng, ctx, 1)
        for r in range(pool.cap):
            if pool.rows[r] is None:
                return pool, r
        r = pool.cap
        pool.grow(self.eng)
        return pool, r

    # -- lifecycle --------------------------------------------------------
    def admit(self, prompt_tokens: list, steps: int,
              sampler: Optional[SamplerConfig] = None,
              stop_tokens: tuple = ()) -> int:
        """Prefill ``prompt_tokens`` into a free row and return its handle
        (uniform mode: the slot index, the historical contract).

        The prompt's prefix runs through the engine's bucketed prefill into
        a fresh single cache, written straight into the row's slab (donated
        in-place update); the last prompt token stays pending so the row's
        first fused step samples from the final-prompt-position logits with
        the FIRST key of a fresh PRNGKey(sampler.seed) chain — the exact
        schedule a solo ``generate`` walks (``sampler`` defaults to the
        engine's SamplerConfig). ``steps``/``stop_tokens`` are this row's
        private budget and stop set, checked per chunk like generate_batch's
        row_steps/stop_tokens.

        Equivalent to ``admit_begin`` + prefill_step(handle, whole-prefix):
        the entire prompt runs before this returns, stalling the pool for
        the whole prefill — use admit_begin/prefill_step when resident rows
        shouldn't wait. Raises RuntimeError when nothing can be admitted
        (check ``can_admit`` / ``free_slots``).
        """
        handle = self.admit_begin(prompt_tokens, steps, sampler=sampler,
                                  stop_tokens=stop_tokens)
        while self._slots[handle].prefilling:
            self.prefill_step(handle, budget=len(prompt_tokens))
        return handle

    def admit_begin(self, prompt_tokens: list, steps: int,
                    sampler: Optional[SamplerConfig] = None,
                    stop_tokens: tuple = ()) -> int:
        """Reserve a row for the prompt WITHOUT prefilling it: the prompt
        is consumed incrementally by ``prefill_step`` calls, interleaved
        with ``step_chunk``, so resident rows keep emitting tokens while a
        long prompt fills its cache. Once live, the row's stream is
        bit-identical to a monolithic admit() of the same request: the
        chunked prefill runs the same bucketed forwards at the same
        positions into the same slab, and the sampler chain starts from the
        same fresh PRNGKey. 1-token prompts have nothing to prefill and go
        live immediately."""
        if self._closed:
            raise RuntimeError("batch session is closed")
        if not prompt_tokens:
            raise ValueError("admit needs a non-empty prompt")
        S = self.eng.cfg.seq_len
        if len(prompt_tokens) > S:
            raise ValueError(
                f"prompt of {len(prompt_tokens)} tokens exceeds seq_len {S}")
        if not self.can_admit(len(prompt_tokens), steps,
                              list(prompt_tokens) if self.paged else None):
            raise RuntimeError(
                f"no free slot (max_batch={self.max_batch}, KV budget "
                f"{self._reserved_tokens}/{self.budget_tokens} tokens); "
                "release a finished row first")
        faults.fire("admit")
        scfg = sampler if sampler is not None else self.eng.sampler_cfg
        if self.paged:
            return self._admit_begin_paged(list(prompt_tokens), steps, scfg,
                                           tuple(stop_tokens))
        plen = len(prompt_tokens)
        reserved = self._bucket_for(self._need_ctx(plen, steps))
        # place optimistically small: enough for the prompt plus one decode
        # chunk of headroom — early-stopping rows never touch a big slab;
        # migration (covered by the reservation) grows the long-lived ones
        place = self._bucket_for(min(reserved, plen + self.chunk))
        pool, row = self._alloc_row(place)
        handle = row if not self.bucket_kv else self._next_handle
        self._next_handle += 1
        self._reserved_tokens += reserved
        if self._budget is not None:
            self._budget.reserve(reserved)
            self._budget.place(pool.ctx)
        pos0 = plen - 1
        room = S - pos0
        budget = min(room, steps)
        st = _SlotState(
            room=room, budget=budget, stop_tokens=tuple(stop_tokens),
            reserved=reserved,
            done=budget <= 0, finish="length" if budget <= 0 else None)
        self._slots[handle] = st
        self._where[handle] = (pool, row)
        pool.rows[row] = handle
        if budget <= 0:
            return handle  # never decodes; skip the prefill entirely
        if plen == 1:
            self._go_live(handle, prompt_tokens, scfg)
        else:
            faults.fire("prefill")
            st.prefilling = True
            self._prefills[handle] = _PendingPrefill(
                list(prompt_tokens), scfg, self.eng.new_cache())
        return handle

    def _admit_begin_paged(self, prompt_tokens: list, steps: int,
                           scfg: SamplerConfig, stop_tokens: tuple) -> int:
        """Paged admission: walk the radix tree, alias the cached prefix,
        reserve only the private tail, and prefill only what the cache
        can't serve. The aliased blocks all sit strictly below position
        plen-1 — this row never writes there (write-before-attend starts at
        the pending token), so sharing is read-only by construction and the
        live stream stays bit-identical to a cold prefill."""
        S = self.eng.cfg.seq_len
        plen = len(prompt_tokens)
        faults.fire("prefix_match")
        priv, full, cow = self._plan_pages(plen, steps, prompt_tokens)
        # pin the aliased prefix FIRST: pinning pulls evictable pages out
        # of the availability pool, so the reservation check below is exact
        # with the pins already in place
        for n in full:
            self._alloc.ref(n.page)
        if not self._alloc.can_reserve(priv) or (
                self._budget is not None
                and not self._budget.can_fit(priv * self.page)):
            for n in full:
                self._alloc.unref(n.page)
            raise RuntimeError(
                f"no free KV pages ({self._alloc.free_count} free + "
                f"{self._alloc.evictable_count} evictable, "
                f"{self._alloc.reserved_pages} reserved, need {priv}); "
                "release a finished row first")
        self._alloc.reserve(priv)
        reserved = priv * self.page
        self._reserved_tokens += reserved
        if self._budget is not None:
            self._budget.reserve(reserved)
        need_ctx = self._need_ctx(plen, steps)
        rp = _RowPages([n.page for n in full], priv, need_ctx, plen)
        # place in a window sized for the prompt plus one chunk of headroom
        # — regroup (a host-side table move) widens the long-lived rows
        place = min(need_ctx, plen + self.chunk)
        g, row = self._alloc_prow(
            self._nb_for(paged_kv.pages_for(place, self.page)))
        handle = self._next_handle
        self._next_handle += 1
        pos0 = plen - 1
        room = S - pos0
        budget = min(room, steps)
        st = _SlotState(
            room=room, budget=budget, stop_tokens=stop_tokens,
            reserved=reserved,
            done=budget <= 0, finish="length" if budget <= 0 else None)
        self._slots[handle] = st
        self._where[handle] = (g, row)
        self._rowpages[handle] = rp
        g.rows[row] = handle
        if budget <= 0:
            return handle  # never decodes; pages stay pinned until release
        cached = len(full) * self.page
        if cow is not None:
            # the prompt ends flush on a cached block whose last slot is
            # this row's first write target: duplicate it privately
            p = self._page_alloc(rp)
            self._arena = self.eng._page_copy(
                self._arena, jnp.int32(p), jnp.int32(cow.page))
            rp.blocks.append(p)
            cached = plen - 1
            self.cow_copies += 1
            if self.eng._m_cow is not None:
                self.eng._m_cow.inc()
        matched = min(cached, plen - 1)
        if matched > 0:
            self.prefix_hits += 1
            self.prefix_tokens_matched += matched
            if self.eng._m_prefix_hits is not None:
                self.eng._m_prefix_hits.inc()
                self.eng._m_prefix_tokens.inc(matched)
        else:
            self.prefix_misses += 1
            if self.eng._m_prefix_misses is not None:
                self.eng._m_prefix_misses.inc()
        if plen == 1 or cached >= plen - 1:
            # nothing left to prefill: every attended prefix position is
            # aliased (or COW-copied) — allocate the tail and go live
            self._finish_pages(handle, prompt_tokens)
            self._go_live(handle, prompt_tokens, scfg)
            return handle
        faults.fire("prefill")
        st.prefilling = True
        staging = self.eng.new_cache()
        if full:
            # preload ALL aliased blocks in one gather dispatch so the
            # chunked prefill continues at ``cached`` over the exact KV a
            # cold prefill would have written (the chunked==monolithic
            # invariant then carries) — a W-block warm prefix costs O(1)
            # dispatches, not O(W)
            staging = self.eng._pages_to_single(
                staging, self._arena,
                self._pad_pages([n.page for n in full]),
                jnp.int32(len(full) * self.page))
        pf = _PendingPrefill(prompt_tokens, scfg, staging)
        pf.cursor = cached
        pf.scattered = cached
        # publish-at-admit: allocate the row's fully-prompt-covered tail
        # blocks NOW and hang them in the radix tree ready=False, so a
        # concurrent admit of the same prefix aliases each block the
        # moment the chunk that fills it lands (COW sharing while BOTH
        # rows are live, not only after this row's go-live)
        nins = (plen - 1) // self.page
        for _ in range(len(rp.blocks), nins):
            rp.blocks.append(self._page_alloc(rp))
        pf.pub_nodes = self._radix.publish_pending(
            prompt_tokens, rp.blocks[:nins])
        for n in pf.pub_nodes:
            if n is not None:
                self._alloc.hold(n.page)
        self._prefills[handle] = pf
        return handle

    def prefill_step(self, handle: Optional[int] = None,
                     budget: Optional[int] = None) -> Optional[tuple]:
        """Advance ONE pending chunked admission by up to ``budget`` prompt
        tokens (default: the session's prefill_chunk; the whole remaining
        prefix when neither is set) — one bucketed prefill forward into the
        admission's own single cache, synced before returning so the call
        bounds the scheduler tick. Returns (handle, finished); ``finished``
        True means the row just went live (its slab is written; the next
        step_chunk decodes it). Returns None when nothing is pending.
        Picks the OLDEST pending admission when ``handle`` is None — FIFO,
        so one call per scheduler tick bounds every resident row's stall to
        one prefill chunk of compute."""
        if self._closed:
            raise RuntimeError("batch session is closed")
        if handle is None:
            handle = next((h for h in self._prefills
                           if not self._slots[h].done), None)
            if handle is None:
                return None
        pf = self._prefills.get(handle)
        if pf is None:
            raise ValueError(f"slot {handle} has no pending prefill")
        st = self._slots[handle]
        prefix = pf.prompt[:-1]
        n = budget if budget is not None else self.prefill_chunk
        if n <= 0:
            n = len(prefix) - pf.cursor
        piece = prefix[pf.cursor:pf.cursor + n]
        faults.fire("prefill_chunk")
        t0 = time.perf_counter()
        _, pf.cache = self.eng._prefill_piece(pf.cache, piece, pf.cursor)
        jax.block_until_ready(pf.cache)
        dt = (time.perf_counter() - t0) * 1000.0
        self.prefill_ms += dt
        st.prefill_ms += dt
        if self.eng._m_prefill_chunk is not None:
            self.eng._m_prefill_chunk.observe(dt)
        pf.cursor += len(piece)
        if self.paged:
            self._scatter_published(handle, pf)
        if pf.cursor < len(prefix):
            return handle, False
        # prefix complete: land the filled single cache in the row's KV
        if self.paged:
            # scatter the staging blocks into freshly allocated arena pages
            # (the aliased prefix blocks are already in place) and publish
            # the fully-covered ones to the radix tree
            self._finish_pages(handle, pf.prompt, staging=pf.cache)
        else:
            pool, row = self._where[handle]
            pool.cache = self.eng._batch_cache_insert(
                pool.cache, pf.cache, jnp.int32(row))
        del self._prefills[handle]
        st.prefilling = False
        self._go_live(handle, pf.prompt, pf.scfg)
        if self.eng._m_prefill is not None:
            self.eng._m_prefill.observe(st.prefill_ms)
        return handle, True

    def _scatter_published(self, handle: int, pf: _PendingPrefill) -> None:
        """Land the staging cache's newly completed full blocks in their
        (already published, ready=False) arena pages and flip the nodes
        ready — the other half of publish-at-admit: a concurrent admit
        aliases each block as soon as the prefill chunk that filled it
        returns. One batched scatter per chunk; blocks another row's
        pending node shadowed (pub_nodes None) still get their private
        scatter, they just never become cache."""
        rp = self._rowpages[handle]
        plen = len(pf.prompt)
        nins = (plen - 1) // self.page
        done = min(pf.cursor // self.page, nins)
        start = pf.scattered // self.page
        if done <= start:
            return
        pages, offs = self._pad_pages(
            [rp.blocks[b] for b in range(start, done)],
            [b * self.page for b in range(start, done)])
        self._arena = self.eng._single_to_pages(
            self._arena, pf.cache, pages, offs)
        for b in range(start, done):
            n = pf.pub_nodes[b] if b < len(pf.pub_nodes) else None
            if n is not None:
                n.ready = True
        pf.scattered = done * self.page

    # -- migration (disaggregated serving) --------------------------------
    def export_row(self, handle: int, fire_fault: bool = True) -> dict:
        """Snapshot a live paged row for migration to a sibling replica:
        its page payloads (host numpy, arena leaf order), page-table
        geometry, and the decode state a solo run would carry across the
        next chunk boundary — pending token, position, the row's ADVANCED
        per-row sampler chain, and the budget/stop accounting. Importing
        the snapshot with :meth:`admit_from_export` on a session with the
        same model and chunk size continues the stream bit-identically to
        the row never having moved. The row itself is untouched — the
        caller releases it once the transfer is acknowledged (a failed
        transfer loses nothing). ``fire_fault=False`` skips the
        ``kv_export`` fault seam — the mid-stream checkpoint path fires
        its own ``ckpt_write`` seam instead, so each export flavor is
        drilled (and counted) separately."""
        if not self.paged:
            raise RuntimeError(
                "export_row needs a paged session (--kv-pages)")
        st = self._state(handle)
        if st.prefilling:
            raise RuntimeError(f"slot {handle} is still prefilling")
        if st.done:
            raise RuntimeError(
                f"slot {handle} already finished — nothing to migrate")
        if fire_fault:
            faults.fire("kv_export")
        g, r = self._where[handle]
        rp = self._rowpages[handle]
        idx = jnp.asarray(rp.blocks, jnp.int32)
        leaves = [np.asarray(jnp.take(leaf, idx, axis=1))
                  for leaf in jax.tree.leaves(self._arena)]
        return {
            "page_tokens": self.page,
            "n_blocks": len(rp.blocks),
            "plen": rp.plen,
            "pos": int(g.pos[r]),
            "token": int(g.tokens[r]),
            "keys": [int(g.keys[r, 0]), int(g.keys[r, 1])],
            "temp": float(g.temps[r]),
            "topp": float(g.topps[r]),
            "room": int(st.room),
            "budget": int(st.budget),
            "offered": int(st.offered),
            "emitted": int(st.emitted),
            "stop_tokens": list(st.stop_tokens),
            "leaves": leaves,
        }

    def admit_from_export(self, prompt_tokens: list, snap: dict) -> int:
        """Admit a row exported by a sibling replica WARM: alias every
        full prompt block the local radix cache already holds (the wire
        payload for those blocks is dropped — the local pages are exact),
        allocate private pages for the rest, scatter the imported
        payloads in ONE dispatch, publish the prompt blocks into the
        local radix tree, and arm the row with the carried decode state.
        Decoding then continues bit-identically to the exporting replica
        having kept the row (both replicas run the same serve config, so
        chunk boundaries — and with them the sampler-chain schedule —
        line up). Raises RuntimeError when the local pool can't fit the
        row; the caller falls back to re-prefilling."""
        if not self.paged:
            raise RuntimeError(
                "admit_from_export needs a paged session (--kv-pages)")
        if self._closed:
            raise RuntimeError("batch session is closed")
        if int(snap["page_tokens"]) != self.page:
            raise ValueError(
                f"page size mismatch: wire {snap['page_tokens']} vs "
                f"local {self.page}")
        plen = int(snap["plen"])
        if plen != len(prompt_tokens):
            raise ValueError(
                f"snapshot prompt length {plen} != {len(prompt_tokens)}")
        budget = int(snap["budget"])
        if int(snap["emitted"]) >= budget:
            raise ValueError("snapshot row already finished")
        faults.fire("kv_import")
        nblk = int(snap["n_blocks"])
        cap_tokens = max(plen, plen - 1 + budget)
        total = paged_kv.pages_for(cap_tokens, self.page)
        # alias what the local cache already holds (blocks strictly below
        # plen-1, never written by this row); everything else — the
        # decode-written tail included — imports privately
        path = self._radix.match(prompt_tokens)
        nfull = min(len(path), (plen - 1) // self.page, nblk)
        full = path[:nfull]
        priv = total - nfull
        for n in full:
            self._alloc.ref(n.page)
        if not self._alloc.can_reserve(priv) or (
                self._budget is not None
                and not self._budget.can_fit(priv * self.page)):
            for n in full:
                self._alloc.unref(n.page)
            raise RuntimeError(
                f"no free KV pages for imported row "
                f"({self._alloc.free_count} free + "
                f"{self._alloc.evictable_count} evictable, need {priv})")
        self._alloc.reserve(priv)
        reserved = priv * self.page
        self._reserved_tokens += reserved
        if self._budget is not None:
            self._budget.reserve(reserved)
        rp = _RowPages([n.page for n in full], priv, cap_tokens, plen)
        for b in range(nfull, nblk):
            rp.blocks.append(self._page_alloc(rp))
        if nblk > nfull:
            pages = self._pad_pages(rp.blocks[nfull:nblk])
            m = int(pages.shape[0])
            blob = []
            for leaf in snap["leaves"]:
                x = np.asarray(leaf)[:, nfull:nblk]
                if m > x.shape[1]:
                    pad = np.zeros(
                        (x.shape[0], m - x.shape[1]) + x.shape[2:],
                        x.dtype)
                    x = np.concatenate([x, pad], axis=1)
                blob.append(x)
            self._arena = self.eng._pages_import(
                self._arena, pages,
                jax.tree.unflatten(jax.tree.structure(self._arena), blob))
        g, row = self._alloc_prow(self._nb_for(max(1, len(rp.blocks))))
        handle = self._next_handle
        self._next_handle += 1
        st = _SlotState(
            room=int(snap["room"]), budget=budget,
            stop_tokens=tuple(snap["stop_tokens"]), reserved=reserved,
            offered=int(snap["offered"]), emitted=int(snap["emitted"]))
        self._slots[handle] = st
        self._where[handle] = (g, row)
        self._rowpages[handle] = rp
        g.rows[row] = handle
        g.tokens[row] = int(snap["token"])
        g.pos[row] = int(snap["pos"])
        g.keys[row] = np.asarray(snap["keys"], np.uint32)
        g.temps[row] = float(snap["temp"])
        g.topps[row] = float(snap["topp"])
        self._sync_table(handle)
        # the imported prompt blocks are valid local KV now: publish them
        # so future admits (and imports) of the same prefix alias local
        # pages instead of paying the wire or a re-prefill again
        nins = min((plen - 1) // self.page, len(rp.blocks))
        for p in self._radix.insert(prompt_tokens, rp.blocks[:nins]):
            self._alloc.hold(p)
        matched = nfull * self.page
        if matched > 0:
            self.prefix_hits += 1
            self.prefix_tokens_matched += matched
            if self.eng._m_prefix_hits is not None:
                self.eng._m_prefix_hits.inc()
                self.eng._m_prefix_tokens.inc(matched)
        else:
            self.prefix_misses += 1
            if self.eng._m_prefix_misses is not None:
                self.eng._m_prefix_misses.inc()
        return handle

    def _go_live(self, handle: int, prompt_tokens: list,
                 scfg: SamplerConfig) -> None:
        """Arm the row's decode state: pending last prompt token, position,
        fresh per-row sampler chain — the exact state a monolithic admit
        leaves behind."""
        pool, row = self._where[handle]
        pool.tokens[row] = int(prompt_tokens[-1])
        pool.pos[row] = len(prompt_tokens) - 1
        pool.keys[row] = np.asarray(
            jax.random.PRNGKey(scfg.seed), np.uint32)
        pool.temps[row] = scfg.temperature
        pool.topps[row] = scfg.topp

    def _migrate(self, handle: int) -> None:
        """Move a live row into the next bucket BEFORE it outgrows its
        slab: copy its [L, 1, ctx, kv, hd] slab — its entire attended
        history — into a row of the bigger pool and carry the host decode
        state (pending token, position, sampler chain) unchanged, so the
        stream continues bit-identically. Admission reserved the worst-case
        bucket up front, so migration never oversubscribes the budget."""
        src, srow = self._where[handle]
        S = self.eng.cfg.seq_len
        need = min(S, int(src.pos[srow]) + self.chunk + 1)
        new_ctx = min(b for b in self.buckets
                      if b > src.ctx and b >= need)
        dst, drow = self._alloc_row(new_ctx)
        dst.cache = self.eng._bucket_cache_migrate(
            dst.cache, src.cache, jnp.int32(srow), jnp.int32(drow))
        dst.tokens[drow] = src.tokens[srow]
        dst.pos[drow] = src.pos[srow]
        dst.keys[drow] = src.keys[srow]
        dst.temps[drow] = src.temps[srow]
        dst.topps[drow] = src.topps[srow]
        dst.rows[drow] = handle
        src.rows[srow] = None
        src.pos[srow] = src.ctx - 1
        self._where[handle] = (dst, drow)
        self.migrations += 1
        if self.eng._m_migrations is not None:
            self.eng._m_migrations.inc()
        if self._budget is not None:
            self._budget.migrate(src.ctx, dst.ctx)

    def step_chunk(self) -> dict:
        """Run ONE fused chunk over every occupied pool and return
        {handle: fresh tokens} for every live row — each list is already
        truncated at the row's own budget and (inclusively) at its first
        stop token, and is never empty UNLESS the row was quarantined: a
        healthy live row always nets at least one token per chunk, so
        staggered admission can never starve a row. Rows that just finished
        are marked done (``is_done``) and skip future chunks until
        released; ``finish_reason`` says why. Returns {} without touching
        the device when nothing is live. Mid-prefill rows are skipped until
        their prefill completes.

        Bucketed sessions first migrate any live row that would outgrow its
        slab within this chunk, then run one program per occupied bucket,
        smallest first — a row migrated this tick decodes this tick, in its
        new pool.

        Quarantine: a row whose watchdog flag went non-finite this chunk is
        marked done with finish reason ``"error"`` and emits NOTHING from
        the chunk (its tokens are garbage) — its slot frees at this chunk
        boundary like any finished row, and every other row's stream is
        bit-identical to a run without the poisoned neighbour (per-row
        sampler chains and cache slabs; nothing crosses rows)."""
        if self._closed:
            raise RuntimeError("batch session is closed")
        if not any(not st.done and not st.prefilling
                   for st in self._slots.values()):
            return {}
        faults.fire("step_chunk")
        if self.paged:
            return self._step_chunk_paged()
        S = self.eng.cfg.seq_len
        fresh: dict = {}
        stepped: set = set()
        while True:
            todo = [c for c in sorted(self._pools) if c not in stepped]
            if not todo:
                break
            ctx = todo[0]
            stepped.add(ctx)
            pool = self._pools[ctx]
            if ctx < S:
                # migrate rows that would outgrow this slab within the
                # chunk; rows finishing inside it stay (their writes fit
                # and nothing reads past them afterwards)
                for r in range(pool.cap):
                    h = pool.rows[r]
                    if h is None:
                        continue
                    st = self._slots[h]
                    if st.done or st.prefilling:
                        continue
                    useful = min(self.chunk, st.budget - st.emitted)
                    p = int(pool.pos[r])
                    if ((useful >= self.chunk and p + self.chunk >= ctx)
                            or (useful < self.chunk and p + useful > ctx)):
                        self._migrate(h)
            live = [r for r in range(pool.cap)
                    if pool.rows[r] is not None
                    and not self._slots[pool.rows[r]].done
                    and not self._slots[pool.rows[r]].prefilling]
            if not live:
                continue
            t1 = time.perf_counter()
            chunk, pool.cache, keys, ok = self.eng.batch_loop(len(live))(
                pool.cache, jnp.asarray(pool.tokens),
                jnp.asarray(pool.pos), jnp.asarray(pool.keys),
                jnp.asarray(pool.temps), jnp.asarray(pool.topps),
                self.eng._poison_rows(pool.cap), n_steps=self.chunk)
            arr = np.asarray(chunk)  # [chunk, cap]
            okh = np.asarray(ok)  # [cap]
            pool.tokens = np.array(chunk[-1])  # np.array: writable copies
            pool.keys = np.array(keys)
            # mirror the in-program per-row pin across chunk boundaries
            pool.pos = np.minimum(pool.pos + self.chunk,
                                  ctx - 1).astype(np.int32)
            chunk_ms = (time.perf_counter() - t1) * 1000.0
            self.decode_ms += chunk_ms
            if self.eng._m_chunk is not None:
                self.eng._m_chunk.observe(chunk_ms)
            self._account_chunk(pool, live, arr, okh, fresh)
        return fresh

    def _account_chunk(self, pool, live: list, arr, okh, fresh: dict) -> None:
        """Per-row bookkeeping for one fused chunk's output — shared by the
        slab and paged dispatch paths (identical by design: the accounting
        IS the bit-identity contract, only residency differs)."""
        for r in live:
            h = pool.rows[r]
            st = self._slots[h]
            if not okh[r]:
                st.done = True
                st.finish = "error"
                if self.eng._m_quarantine is not None:
                    self.eng._m_quarantine.inc()
                fresh[h] = []
                continue
            # a context-exhausted row pinned at its last slot: tokens
            # past its room are garbage — generate_batch's accounting
            keep = max(0, min(self.chunk, st.room - st.offered))
            st.offered += self.chunk
            toks = [int(t) for t in arr[:keep, r]]
            take = min(len(toks), st.budget - st.emitted)
            for j in range(take):
                if toks[j] in st.stop_tokens:
                    take = j + 1
                    break
            toks = toks[:take]
            st.emitted += len(toks)
            if st.emitted >= st.budget:
                st.done = True
                st.finish = "length"
            elif (st.stop_tokens and toks
                    and toks[-1] in st.stop_tokens):
                st.done = True
                st.finish = "stop"
            fresh[h] = toks

    def _step_chunk_paged(self) -> dict:
        """One fused chunk over every occupied window group. Phase 1
        extends every live row's page table ahead of this chunk's writes
        (appending pages — never copying — and regrouping rows whose table
        outgrew their window, a pure host-side move); phase 2 runs one
        gather-windowed program per occupied shape. A live write target is
        therefore always allocated before dispatch; only the discarded
        post-finish garbage steps ever land on the scratch page."""
        fresh: dict = {}
        for h, st in list(self._slots.items()):
            if st.done or st.prefilling:
                continue
            g, r = self._where[h]
            rp = self._rowpages[h]
            p = int(g.pos[r])
            needed = min(p + self.chunk + 1, rp.cap_tokens)
            while len(rp.blocks) < paged_kv.pages_for(needed, self.page):
                rp.blocks.append(self._page_alloc(rp))
            nb = self._nb_for(len(rp.blocks))
            if nb > g.nb:
                self._regroup(h, nb)
            else:
                self._sync_table(h)
        for nb in sorted(self._pgroups):
            g = self._pgroups[nb]
            live = [r for r in range(g.cap)
                    if g.rows[r] is not None
                    and not self._slots[g.rows[r]].done
                    and not self._slots[g.rows[r]].prefilling]
            if not live:
                continue
            W = nb * self.page
            t1 = time.perf_counter()
            chunk, self._arena, keys, ok = self.eng.paged_loop(len(live))(
                self._arena, jnp.asarray(g.tables),
                jnp.asarray(g.tokens), jnp.asarray(g.pos),
                jnp.asarray(g.keys), jnp.asarray(g.temps),
                jnp.asarray(g.topps), self.eng._poison_rows(g.cap),
                n_steps=self.chunk)
            arr = np.asarray(chunk)  # [chunk, cap]
            okh = np.asarray(ok)  # [cap]
            g.tokens = np.array(chunk[-1])
            g.keys = np.array(keys)
            # mirror the in-program per-row pin across chunk boundaries
            g.pos = np.minimum(g.pos + self.chunk, W - 1).astype(np.int32)
            chunk_ms = (time.perf_counter() - t1) * 1000.0
            self.decode_ms += chunk_ms
            if self.eng._m_chunk is not None:
                self.eng._m_chunk.observe(chunk_ms)
            self._account_chunk(g, live, arr, okh, fresh)
        return fresh

    def cancel(self, slot: int) -> None:
        """Stop decoding ``slot``'s row NOW (cancellation / deadline expiry):
        the row is marked done so the next ``step_chunk`` excludes it from
        the live set — exactly the state a budget-exhausted row reaches, so
        no new invariants: it rides along pinned until ``release()`` frees
        its slab (the serving scheduler releases at the same chunk boundary
        it cancels at). Cancelling a mid-prefill admission drops its
        half-filled single cache immediately — the partially written slab
        is garbage the next occupant overwrites before attending.
        Idempotent on an already-done row."""
        st = self._state(slot)
        st.done = True
        pf = self._prefills.pop(slot, None)
        if pf is not None:
            st.prefilling = False
            if self.paged:
                # retract the publish-at-admit nodes this prefill never
                # filled: their pages hold garbage no admit may alias
                self._radix.unpublish(
                    [n for n in pf.pub_nodes
                     if n is not None and not n.ready], self._alloc)
            for leaf in jax.tree.leaves(pf.cache):
                leaf.delete()

    def release(self, slot: int) -> None:
        """Free the row for the next admission and return its KV
        reservation to the budget. The slab is NOT cleared (see class
        docstring for why reuse is safe); the row re-pins at its slab's
        last slot like a free row."""
        st = self._slots.pop(slot, None)
        if st is None:
            raise ValueError(f"slot {slot} is not occupied")
        pf = self._prefills.pop(slot, None)
        if pf is not None:
            if self.paged:
                self._radix.unpublish(
                    [n for n in pf.pub_nodes
                     if n is not None and not n.ready], self._alloc)
            for leaf in jax.tree.leaves(pf.cache):
                leaf.delete()
        pool, row = self._where.pop(slot)
        pool.rows[row] = None
        if self.paged:
            # drop the row's holds: private pages published to the radix
            # tree become evictable cache (their KV survives for future
            # admits), unpublished ones go straight back to the free list
            rp = self._rowpages.pop(slot)
            for p in rp.blocks:
                self._alloc.unref(p)
            self._alloc.unreserve(rp.outstanding)
            pool.pos[row] = pool.nb * self.page - 1
            pool.tables[row, :] = paged_kv.SCRATCH_PAGE
        else:
            pool.pos[row] = pool.ctx - 1
        self._reserved_tokens -= st.reserved
        if self._budget is not None:
            self._budget.release(st.reserved)
            if not self.paged:
                self._budget.unplace(pool.ctx)

    def close(self) -> None:
        """Drop every resident slab's (and pending prefill's) device
        buffers and hand all reservations back to the external budget.
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._budget is not None:
            for st in self._slots.values():
                self._budget.release(st.reserved)
            if not self.paged:
                for pool, _ in self._where.values():
                    self._budget.unplace(pool.ctx)
        for pf in self._prefills.values():
            for leaf in jax.tree.leaves(pf.cache):
                leaf.delete()
        for pool in self._pools.values():
            for leaf in jax.tree.leaves(pool.cache):
                leaf.delete()
            pool.cache = None
        if self.paged:
            # hand every page back before the arena dies: the allocator may
            # be the serving accountant's (KVBudget.attach_pages), and its
            # gauges must not report cached pages of a deleted arena
            for rp in self._rowpages.values():
                for p in rp.blocks:
                    self._alloc.unref(p)
                self._alloc.unreserve(rp.outstanding)
            self._radix.evict(self._alloc.num_pages, self._alloc)
            for leaf in jax.tree.leaves(self._arena):
                leaf.delete()
            self._arena = None
            self._pgroups = {}
            self._rowpages = {}
        self._pools = {}
        self._slots = {}
        self._where = {}
        self._prefills = {}


class _NgramIndex:
    """Incremental n-gram -> latest-start-position index over the consumed
    context: O(1) amortized per appended token, O(1) per draft lookup. A
    naive backward scan is O(context) per verify step, which on a
    near-context-limit chat burns milliseconds of host time per device
    dispatch — eroding exactly the bandwidth win drafting exists to buy."""

    def __init__(self, ngram: int):
        self.ngram = ngram
        self.ctx: list = []
        self._pos: dict = {}
        self._prev: dict = {}  # the occurrence before the latest, per n-gram

    def extend(self, tokens) -> None:
        for t in tokens:
            self.ctx.append(t)
            if len(self.ctx) >= self.ngram:
                key = tuple(self.ctx[-self.ngram:])
                if key in self._pos:
                    self._prev[key] = self._pos[key]
                self._pos[key] = len(self.ctx) - self.ngram

    def draft(self, pending: int, k: int) -> list:
        """Up to k proposed continuations of context + [pending]: what
        followed the most recent earlier occurrence of its trailing n-gram.
        If the latest occurrence ends flush at the end of the context (its
        continuation is empty — the norm on repeated-token runs, the most
        draftable text there is), fall back to the one before it, whose
        continuation is never empty."""
        if k <= 0 or len(self.ctx) + 1 <= self.ngram:
            return []
        tail = tuple((self.ctx + [pending])[-self.ngram:])
        for j in (self._pos.get(tail), self._prev.get(tail)):
            if j is not None:
                cont = self.ctx[j + self.ngram : j + self.ngram + k]
                if cont:
                    return list(cont)
        return []
